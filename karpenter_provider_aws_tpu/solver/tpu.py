"""The TPU solver: constraint-tensor FFD behind the Solver interface.

Pipeline: ``encode_snapshot`` (models/encoding.py) → group-scan kernel
(ops/ffd_jax.py on device, or the numpy twin ops/ffd.py) → decode back to
``SolveResult``. Decisions are identical to the CPU oracle
(tests/test_solver_equivalence.py enforces fingerprint equality).

Coverage of the BASELINE configs: 1/2/5 (homogeneous FFD, mixed
selectors/taints over the full catalog, spot/on-demand with weights &
limits) run the packed single-buffer device scan; config 3 (topology
spread + pod (anti-)affinity) runs the exact tensor pour of ops/topo.py
on host state; unsupported topology shapes (non-zone/hostname keys,
zone-id mixed with topology) fall back to the CPU oracle. Device dispatch
is a hook (``_dispatch``) so the sidecar's RemoteSolver can ride gRPC.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..apis import labels as L
from ..apis.requirements import IN, Requirement, Requirements
from ..apis.resources import Resources
from ..models.delta import DeltaEncoder, full_existing_encode
from ..models.encoding import SnapshotEncoding, encode_snapshot
from ..ops import ffd
from .cpu import CPUSolver
from .route import DEV_FAILED_MS, Router, routed
from .types import (ExistingNode, NewNodeClaim, SchedulingSnapshot,
                    SolveResult, Solver)


def _slotmap(E: int, Ep: int, N: int) -> np.ndarray:
    """Row indices that drop the dead padded existing slots [E, Ep)."""
    return np.concatenate([np.arange(E), np.arange(Ep, N)])


class TopoKernelBail(RuntimeError):
    """The topology device kernel left its static event envelope for this
    snapshot; the caller must serve it from the host pour instead."""


class DeviceDispatchFailed(RuntimeError):
    """The device engine failed MID-DISPATCH (sidecar died mid-call,
    retries exhausted, breaker open). The host twin is decision-identical
    so the caller serves from it — under backend='auto' the router's
    exception handling already does; backend='jax' catches this
    explicitly so an explicit device request degrades instead of
    crashing the solve."""


def _runs_from_events(ev, gi: int):
    """Reconstruct the host pour's placement-run list from the device
    event log (ops/topo_jax.py kinds). The reconstruction mirrors the
    host engine's exact bookkeeping: consecutive same-slot runs merge
    (ops/topo.py:_commit), a cyc entry's pattern is the last `p` events
    of the (host-equivalent) event tail, and the tail grows by the
    pattern after a jump exactly as the host's event_log does."""
    from ..ops.topo_jax import K_ANTIRUN, K_CYC
    n = int(ev["n"][gi])
    runs = []
    tail = []  # (slot, len) of the host-equivalent event log tail
    for i in range(n):
        kind = int(ev["kind"][gi, i])
        slot = int(ev["slot"][gi, i])
        ln = int(ev["len"][gi, i])
        if kind == K_CYC:
            p = ln
            k = int(ev["aux"][gi, i])
            pattern = tail[-p:]
            runs.append(("cyc", list(pattern), k))
            tail.extend(pattern * (k if k < 3 else 2))
        elif kind == K_ANTIRUN:
            for j in range(ln):
                runs.append((slot + j, 1))
                tail.append((slot + j, 1))
        else:  # place / fix / open
            if runs and runs[-1][0] == slot:
                runs[-1] = (slot, runs[-1][1] + ln)
            else:
                runs.append((slot, ln))
            tail.append((slot, ln))
        if len(tail) > 64:
            del tail[:len(tail) - 64]
    return runs


class TPUSolver(Solver):
    name = "tpu"
    #: the pruned G-axis kernel runs the solve locally; the sidecar's
    #: RemoteSolver (whose dispatches ride gRPC to a server that only
    #: speaks the base kernel) turns this off
    supports_pruned_kernel = True
    #: the checkpointed/suffix incremental kernels dispatch locally; the
    #: RemoteSolver turns this off — its server keeps ITS OWN resident
    #: checkpoint bank per patched arena and consumes the frontier it
    #: derives from the SolvePatch sections (sidecar/server.py), so the
    #: delta wire and incremental solve compose without a new RPC
    supports_ckpt_kernel = True

    def __init__(self, backend: str = "auto", n_max: int = 2048,
                 incremental: bool = True):
        """backend: 'auto' (cost-routed, see solver/route.py), 'jax'
        (always the device scan kernel) or 'numpy' (always the host twin —
        same math, decision-identical by the equivalence suites).

        n_max sizes the new-node slot arrays per solve. It is a CAPACITY,
        not a decision bound: a solve that exhausts every slot with pods
        left over GROWS n_max (x4, capped at the pod count — each new
        node hosts >= 1 pod, so that cap is loss-free) and re-runs, so
        decisions always match the oracle, which opens nodes unboundedly.
        Default 2048 vs the 500-node scale envelope (SURVEY §6) means the
        growth path is cold in production.

        incremental: keep the last solve's encoding (and packed device
        arena) RESIDENT and dirty-patch it per solve (models/delta.py)
        instead of re-encoding from scratch — byte-identical arenas by
        the fuzz-parity contract. Off is the from-scratch oracle path
        (bench baseline, bisection)."""
        assert backend in ("auto", "jax", "numpy")
        self.backend = backend
        self.n_max = n_max
        #: incremental encoder (None = from-scratch every solve). Holds
        #: the resident SnapshotEncoding + existing tables + epoch.
        self._delta = DeltaEncoder() if incremental else None
        #: evidence of the LAST encode's delta classification
        #: (SnapshotDelta) — bench/phase-stats honesty marker
        self._last_delta = None
        #: resident packed device arena: dict(enc, arrays, stt, buf,
        #: bflat, ndev, version) — reused/patched by _run_jax when the
        #: delta tier proves the shape class unchanged
        self._pack_cache = None
        #: resident device checkpoint bank (solver/incremental.py): the
        #: last eligible full solve's per-chunk entry carries, device-
        #: resident (never crosses the wire), plus host copies of its
        #: padded takes/leftover for suffix splicing. dict(key, CK,
        #: token, bank, takes, leftover) — see _try_suffix/_adopt_bank
        self._ckpt_bank = None
        #: host twin of the bank for the numpy engine: NodeState
        #: checkpoints (ops/ffd.py snapshot_state) at the same chunk
        #: stride, exact (unbucketed) resume — identical_decisions
        #: parity holds on device-free hosts
        self._host_bank = None
        #: honesty marker of the LAST solve: "full" or
        #: "suffix@<bucket>" — surfaced as last_phase_stats["solve"]
        self._solve_mode = "full"
        #: (statics, n_bucket) keys whose suffix shape-class ladder has
        #: been pre-compiled (_prime_suffix): bank adoption compiles
        #: every bucket once so no measured warm tick ever pays a trace
        self._suffix_primed = set()
        #: BASE device group-scan cap: beyond this padded group count the
        #: full [N, T]-per-step kernel is never dispatched (its run time
        #: is O(G * N * T)). See docs/solver-design.md "The G axis".
        self.dev_max_groups = 4096
        #: PRUNED-kernel cap (ops/ffd_jax.py solve_scan_packed1_pruned):
        #: the bound-pass + S-slot-exact step costs O(N*D + S*T*D), so
        #: the device G envelope quadruples. Solves between the two caps
        #: ride the pruned kernel (single device, no minValues floors);
        #: a pruning-insufficient solve BAILS to the host twin, so
        #: decisions never depend on which kernel served.
        self.dev_max_groups_pruned = 16384
        #: exact-slot budget per pruned-kernel step (see the constant's
        #: sizing rationale in ops/hostpack.py); injected at the _run_jax
        #: dispatch site so the RemoteSolver override ships it on the
        #: SolvePruned wire too
        from ..ops.hostpack import DEV_FUSE, DEV_PRUNED_SLOTS
        self.dev_pruned_slots = DEV_PRUNED_SLOTS
        #: fused-group block width of the base kernel (ops/ffd_jax.py
        #: _solve_fused): groups the encoder proves pairwise pool/
        #: existing-disjoint batch dev_fuse per scan step, cutting the
        #: trip count dev_fuse-fold. 0/1 disables. Gated per solve on
        #: no minValues floors and a single device (mesh and pruned
        #: kernels keep their own scan shapes).
        self.dev_fuse = DEV_FUSE
        #: below this padded group count the UNFUSED kernel serves: tiny
        #: scans sit on the dispatch latency floor, not the trip count,
        #: and the fused step body costs ~2x dev_fuse the compile time —
        #: not worth paying for single-group solves
        self.dev_fuse_min_groups = 64
        #: evidence from the LAST device dispatch (bench engine report):
        #: kernel path, per-dispatch batch size, scan trip count and the
        #: fused/sequential block split of the fused kernel
        self.last_dispatch_stats: dict = {}
        #: per-phase wall split of the LAST solve (bench evidence):
        #: encode_ms (snapshot -> tensors, host side), kernel_ms (pack +
        #: engine dispatch + unpack — the wire round trip for the
        #: RemoteSolver), decode_ms (tensors -> SolveResult)
        self.last_phase_stats: dict = {}
        # resolve the native fill at CONSTRUCTION, not mid-solve: the
        # binding's one-shot build attempt (repo convention, codec.py)
        # must never appear as a first-solve latency cliff, and running
        # without it deserves one visible line, not silence
        from ..native import fastfill as _fastfill
        if not _fastfill.available():
            import logging
            logging.getLogger(__name__).info(
                "native fastfill unavailable (no compiler or build "
                "failed); high-cardinality solves use the numpy path")
        # same convention for the grouping-walk extension: its one-shot
        # build must never appear as a first-solve latency cliff
        from ..models.encoding import _groupwalk
        _groupwalk()
        self._router = Router(name="solver")
        #: current new-node slot bucket; grows on overflow, sticky across
        #: solves (steady-state clusters reuse the same compiled kernel)
        self._bucket = min(256, n_max)
        #: new-node counts of recent solves — the shrink window. Carry
        #: width is pure scan-body cost every tick, so after a burst
        #: the bucket must come back down; see _run_jax.
        self._bucket_peaks: list = []
        self._cpu_fallback = CPUSolver()
        #: optional metrics registry (operator injects); fallbacks to the
        #: sequential oracle are a perf cliff and must never be silent
        self.metrics = None

    def _oracle_fallback(self, snapshot: SchedulingSnapshot,
                         reason: str) -> SolveResult:
        import logging
        logging.getLogger(__name__).warning(
            "TPU solver falling back to CPU oracle (%s): %d pods",
            reason, len(snapshot.pods))
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_oracle_fallback_total",
                             labels={"reason": reason})
        return self._cpu_fallback.solve(snapshot)

    def solve(self, snapshot: SchedulingSnapshot) -> SolveResult:
        """Slot growth (``_grow_if_exhausted``) is scoped to ONE solve:
        it persists across the preference wrapper's relax rounds (they
        re-solve the same workload) but resets afterwards — a single
        pathological snapshot must not permanently inflate every later
        solve's state arrays to its size."""
        orig_n_max = self.n_max
        try:
            return super().solve(snapshot)
        finally:
            self.n_max = orig_n_max
            self._bucket = min(self._bucket, orig_n_max)

    def _grow_if_exhausted(self, snapshot: SchedulingSnapshot,
                           leftover, final) -> bool:
        """True iff the solve ran out of new-node slots with pods left
        over AND growing can help — the caller then re-solves with 4x
        slots. Closes the one spot where the tensor path could silently
        diverge from the oracle (which opens nodes unboundedly): overflow
        pods must never be reported unschedulable just because the slot
        arrays were sized too small."""
        if self.n_max >= len(snapshot.pods):
            return False  # nodes <= pods: genuine unschedulability
        if int(np.asarray(leftover).sum()) <= 0:
            return False
        alive = np.asarray(final["alive"])
        if int(alive[final["E"]:].sum()) < self.n_max:
            return False  # slots to spare: leftovers are real
        self.n_max = min(self.n_max * 4, len(snapshot.pods))
        self._bucket = min(self._bucket, self.n_max)
        import logging
        logging.getLogger(__name__).info(
            "new-node slots exhausted with pods left over; growing "
            "n_max to %d and re-solving", self.n_max)
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_slot_growth_total")
        return True

    # ------------------------------------------------------------------
    def _solve_core(self, snapshot: SchedulingSnapshot,
                    pod_groups=None) -> SolveResult:
        if not snapshot.pods:
            return SolveResult(new_nodes=[], existing_assignments={},
                               unschedulable={})
        import time as _time
        _t0 = _time.perf_counter()
        existing = sorted(snapshot.existing_nodes, key=lambda n: n.name)
        if self._delta is not None:
            self._delta.metrics = self.metrics
            if self.metrics is not None:
                from ..native import deltawalk as _dwalk
                _dwalk.attach_metrics(self.metrics)
            enc, (ex_alloc, ex_used, ex_compat), self._last_delta = \
                self._delta.encode(snapshot, pod_groups, existing)
        else:
            enc = encode_snapshot(snapshot, pod_groups=pod_groups)
            ex_alloc, ex_used, ex_compat = \
                full_existing_encode(enc, existing)
            self._last_delta = None
        # topology detection is per GROUP (~tens), not per pod (~50k): the
        # pod-group signature includes spread/affinity terms, so the group
        # representative is authoritative for every member (the flag is
        # computed in the encoder's signature row bank — no group scan)
        topo = enc.topo_any
        # T == 0 (e.g. consolidation's price-filtered deletion check
        # empties every pool): no new nodes are possible, but pods may
        # still land on existing nodes. The HOST engines handle the
        # zero-width type axis exactly (candidate rows are empty,
        # existing-slot fills use concrete allocatable); only the device
        # kernels need T > 0, so such a solve is pinned to the host twin
        # below — including the topology pour, which keeps
        # consolidation's topology-bearing deletion checks on the tensor
        # engine instead of the sequential oracle.
        host_only = not enc.types
        if topo:
            from ..ops.topo import build_topo_encoding
            tenc = build_topo_encoding(enc, snapshot, existing)
            if not tenc.supported:
                return self._oracle_fallback(snapshot, "unsupported-topology")
            _t_enc = _time.perf_counter()

            def host_pour():
                return self._run_numpy(enc, ex_alloc, ex_used, ex_compat,
                                       tenc=tenc, existing=existing)

            group_cap = len(enc.groups) > self.dev_max_groups
            if group_cap and self.backend != "numpy":
                # same non-silent cliff contract as the non-topo branch
                import logging
                logging.getLogger(__name__).info(
                    "group count %d exceeds dev_max_groups=%d; topology "
                    "solve serves from the host pour", len(enc.groups),
                    self.dev_max_groups)
                if self.metrics is not None:
                    self.metrics.inc(
                        "karpenter_solver_device_fallback_total",
                        labels={"reason": "group_cap"})
            lowerable = not host_only and not group_cap \
                and self._topo_lowerable(enc, tenc, existing)
            if self.backend == "numpy" or not lowerable:
                takes, leftover, final = host_pour()
            elif self.backend == "jax":
                from .route import dev_engine_usable
                if dev_engine_usable(self._router):
                    try:
                        takes, leftover, final = self._run_jax_topo(enc, tenc)
                    except TopoKernelBail:
                        takes, leftover, final = host_pour()
                else:
                    takes, leftover, final = host_pour()
            else:  # auto: cost-route host pour vs device event kernel
                self._router.metrics = self.metrics
                takes, leftover, final = routed(
                    self._router,
                    self._bucket_key(enc, ex_alloc.shape[0]) + ("topo",),
                    host_pour,
                    lambda: self._run_jax_topo(enc, tenc))
            if self._grow_if_exhausted(snapshot, leftover, final):
                return self._solve_core(snapshot, pod_groups=pod_groups)
            _t_k = _time.perf_counter()
            res = self._decode(enc, existing, takes, leftover, final)
            self._set_phase_stats(_t0, _t_enc, _t_k)
            return res
        _t_enc = _time.perf_counter()
        if host_only or len(enc.groups) > self._dev_group_cap(enc):
            # zero-width type axis (host engines only), or beyond the
            # device group caps (base 4096, pruned 16384 — the G-axis
            # law, docs/solver-design.md): host engine only. A latency
            # or engine cliff must never be silent, even when requested
            # via backend="jax"
            if self.backend != "numpy" and not host_only:
                import logging
                logging.getLogger(__name__).info(
                    "group count %d exceeds the effective device group "
                    "cap %d; serving from the host engine",
                    len(enc.groups), self._dev_group_cap(enc))
                if self.metrics is not None:
                    self.metrics.inc(
                        "karpenter_solver_device_fallback_total",
                        labels={"reason": "group_cap"})
            takes, leftover, final = self._run_numpy(
                enc, ex_alloc, ex_used, ex_compat)
        elif self.backend == "jax":
            # explicit device requests still go through the NONBLOCKING
            # liveness verdict (route.dev_engine_usable): a wedged link
            # or an in-flight probe falls back to the bit-identical host
            # twin for this solve — never a hang, never silent
            from .route import dev_engine_usable
            if dev_engine_usable(self._router):
                try:
                    takes, leftover, final = self._run_jax(
                        enc, ex_alloc, ex_used, ex_compat)
                except DeviceDispatchFailed as e:
                    # dev engine died mid-dispatch (sidecar gone, link
                    # dropped): the bit-identical host twin serves, and
                    # the parked EWMA keeps auto-routing off the device
                    import logging
                    logging.getLogger(__name__).warning(
                        "device dispatch failed (%s); serving from the "
                        "host twin", e)
                    if self.metrics is not None:
                        self.metrics.inc(
                            "karpenter_solver_device_fallback_total",
                            labels={"reason": "dispatch_failed"})
                    self._router.observe(
                        self._bucket_key(enc, ex_alloc.shape[0]),
                        "dev", DEV_FAILED_MS)
                    takes, leftover, final = self._run_numpy(
                        enc, ex_alloc, ex_used, ex_compat)
            else:
                import logging
                logging.getLogger(__name__).warning(
                    "dev engine unavailable (probe pending or link "
                    "dead); solving on the host twin")
                if self.metrics is not None:
                    self.metrics.inc(
                        "karpenter_solver_device_fallback_total",
                        labels={"reason": "device_unavailable"})
                takes, leftover, final = self._run_numpy(
                    enc, ex_alloc, ex_used, ex_compat)
        elif self.backend == "numpy":
            takes, leftover, final = self._run_numpy(enc, ex_alloc, ex_used, ex_compat)
        else:  # auto: route host twin vs device kernel by measured cost
            self._router.metrics = self.metrics
            takes, leftover, final = routed(
                self._router, self._bucket_key(enc, ex_alloc.shape[0]),
                lambda: self._run_numpy(enc, ex_alloc, ex_used, ex_compat),
                lambda: self._run_jax(enc, ex_alloc, ex_used, ex_compat))
        if self._grow_if_exhausted(snapshot, leftover, final):
            return self._solve_core(snapshot, pod_groups=pod_groups)
        _t_k = _time.perf_counter()
        res = self._decode(enc, existing, takes, leftover, final)
        self._set_phase_stats(_t0, _t_enc, _t_k)
        return res

    def _set_phase_stats(self, t0: float, t_enc: float,
                         t_kernel: float) -> None:
        """Record the encode/kernel/decode wall split of the solve that
        just landed (kernel covers pack + dispatch + unpack — for the
        RemoteSolver that is the wire round trip). Bench reads it next
        to last_dispatch_stats; a grown re-solve records only its final
        attempt, matching the headline the caller saw."""
        import time as _time
        now = _time.perf_counter()
        self.last_phase_stats = dict(
            encode_ms=(t_enc - t0) * 1e3,
            kernel_ms=(t_kernel - t_enc) * 1e3,
            decode_ms=(now - t_kernel) * 1e3,
            # incremental-solve honesty marker: "full" or
            # "suffix@<bucket>" — a sub-ms kernel_ms without it would
            # be unfalsifiable, exactly like the encode tier below
            solve=self._solve_mode)
        d = self._last_delta
        if d is not None:
            # honesty marker for bench/memo evidence: how the encode was
            # served (hit/rows/groups/full) and how much it patched — a
            # near-zero encode_ms without the marker would be unfalsifiable
            self.last_phase_stats["cache"] = d.tier
            self.last_phase_stats["patched_rows"] = d.patched_rows

    def _dev_group_cap(self, enc: SnapshotEncoding) -> int:
        """Effective device group cap for this snapshot: the pruned
        kernel's envelope when it is eligible (local dispatch, single
        device, no minValues floors), else the base kernel's."""
        if (self.supports_pruned_kernel and enc.mv_K == 0
                and self._dev_devices() <= 1):
            return self.dev_max_groups_pruned
        return self.dev_max_groups

    def _settle_bucket(self, n_bucket: int, used_slots: int) -> int:
        """Sticky-bucket SHRINK — the x4 grow loop's mirror. The slot
        bucket only ever grew, so one burst solve left every later
        steady-state tick paying a 256-wide scan carry for the ~5 new
        nodes it actually places (measured: 19ms vs 12ms at the 50k
        warm-tick shape). Track the new-node peak over the last 8
        solves and step the bucket back down the same 16/64/256 ladder
        the grow loop walks — but only while the peak keeps 4x headroom
        at the width below, so a recurring burst never oscillates (each
        width is its own compiled kernel; flapping would recompile)."""
        self._bucket_peaks.append(int(used_slots))
        if len(self._bucket_peaks) > 8:
            self._bucket_peaks.pop(0)
        if len(self._bucket_peaks) == 8:
            peak = max(max(self._bucket_peaks), 1)
            while n_bucket > 16 and peak * 4 <= n_bucket // 4:
                n_bucket //= 4
        return n_bucket

    def _bucket_key(self, enc: SnapshotEncoding, E: int) -> Tuple:
        """Shape bucket = the padded statics that key the XLA compile
        cache (_run_jax's pow2 bucketing) + the dev-engine device count
        (the mesh solve is its own engine with its own latency curve), so
        router stats live exactly as long as a compiled kernel does."""
        G, T = len(enc.groups), len(enc.types)
        Gp = max(1, 1 << (G - 1).bit_length())
        Ep = 1 << (E - 1).bit_length() if E else 0
        Pp = max(1, 1 << (len(enc.pools) - 1).bit_length())
        return (T, max(8, len(enc.dims)), len(enc.zones), Gp, Ep, Pp,
                enc.mv_K, 1 if enc.prio is not None else 0,
                self._dev_devices())

    # ------------------------------------------------------------------
    def _encode_existing(self, enc: SnapshotEncoding,
                         existing: Sequence[ExistingNode]):
        """From-scratch existing-node tables. The body lives in
        models/delta.py (``full_existing_encode``) so the incremental
        paths and this oracle share one derivation."""
        return full_existing_encode(enc, existing)

    # ------------------------------------------------------------------
    def _try_host_suffix(self, enc, ex_alloc, d, CK):
        """Host twin of _try_suffix: restore the deepest NodeState
        checkpoint at or below the dirty frontier and re-fill only the
        suffix groups — EXACT resume depth (the host pays no compile,
        so no bucket ladder). Returns ``((takes, leftover, final),
        reason)`` or ``(None, reason)``."""
        hb = self._host_bank
        if hb is None:
            return None, "cold"
        if not (hb["enc"] is enc and hb["E"] == ex_alloc.shape[0]
                and hb["n_max"] == self.n_max):
            return None, "shape"
        tok = self._bank_prev_token()
        if hb["token"] != tok:
            return None, ("epoch" if tok is not None
                          and hb["token"][0] != tok[0] else
                          "version_lag")
        if d.dirty_frontier <= 0:
            return None, "frontier"
        G = len(enc.groups)
        j = min(d.dirty_frontier // CK, len(hb["snaps"]) - 1)
        s0 = j * CK
        st = hb["st"]
        ffd.restore_state(st, hb["snaps"][j])
        takes, leftover = hb["takes"], hb["leftover"]
        for gi in range(s0, G):
            if gi % CK == 0:
                hb["snaps"][gi // CK] = ffd.snapshot_state(st)
            take, rem = ffd.fill_group_closed_form(st, enc, gi)
            takes[gi] = take
            leftover[gi] = rem
        hb["token"] = self._delta.state_token()
        self._solve_mode = f"suffix@{G - s0}"
        m = self.metrics
        if m is not None:
            m.inc("karpenter_solver_solve_suffix_total",
                  labels={"reason": d.tier})
            m.observe("karpenter_solver_solve_suffix_groups",
                      float(G - s0))
        # copies throughout: the resident st/takes mutate on future
        # ticks, and the caller's result must not alias them
        final = dict(types=st.types.copy(), zones=st.zones.copy(),
                     ct=st.ct.copy(), pool=st.pool.copy(),
                     alive=st.alive.copy(), used=st.used.copy(),
                     E=st.E, run_log={}, zfix=None)
        return (takes.copy(), leftover.copy(), final), d.tier

    def _run_numpy(self, enc, ex_alloc, ex_used, ex_compat,
                   tenc=None, existing=()):
        self._solve_mode = "full"
        from .incremental import CKPT_CHUNK, CKPT_MAX_GROUPS
        G = len(enc.groups)
        d = self._last_delta if self._delta is not None else None
        # host-twin incremental gate: warm (hit/rows) ticks at bankable
        # group counts run the per-group engine WITH checkpoints even
        # when the fastfill one-shot could serve — paying one recorded
        # full solve buys every later warm tick a suffix-only re-fill,
        # which beats re-running fastfill over all G groups. Cold/
        # structural ticks keep the fastfill fast path.
        host_ck = (tenc is None and d is not None
                   and d.tier in ("hit", "rows")
                   and 2 * CKPT_CHUNK <= G <= CKPT_MAX_GROUPS)
        hreason = "disabled"
        if host_ck:
            served, hreason = self._try_host_suffix(enc, ex_alloc, d,
                                                    CKPT_CHUNK)
            if served is not None:
                return served
        st = ffd.NodeState.create(enc, self.n_max, ex_alloc, ex_used, ex_compat)
        if not host_ck and tenc is None and enc.mv_floor is None \
                and all(pe.limit_vec is None for pe in enc.pools):
            # the whole solve fits the fast-path guards: run every
            # group's fill in ONE native call (the G-axis scaling law —
            # a 10k-signature snapshot costs ~10k interpreted group
            # fills otherwise; see native/fastfill.cpp). Decision
            # identity is fuzz-enforced against both python engines.
            from ..native import fastfill
            if fastfill.available():
                out = fastfill.fill_all(st, enc)
                if out is not None:
                    placements, leftover_v = out
                    final = dict(types=st.types, zones=st.zones,
                                 ct=st.ct, pool=st.pool, alive=st.alive,
                                 used=st.used, E=st.E, run_log={},
                                 zfix=None, placements=placements)
                    return None, leftover_v, final
                # triple-buffer overflow: the native call mutated st
                # mid-walk, so the interpreted path below must start
                # from FRESH state (decisions, not just perf, depend
                # on it)
                st = ffd.NodeState.create(enc, self.n_max, ex_alloc,
                                          ex_used, ex_compat)
        ts = None
        if tenc is not None:
            from ..ops.topo import TopoState, fill_group_topo, \
                record_plain_fill
            ts = TopoState.create(tenc, st.Z, st.N, st.E, existing)
        takes = np.zeros((len(enc.groups), st.N), dtype=np.int64)
        leftover = np.zeros(len(enc.groups), dtype=np.int64)
        run_log = {}
        snaps = [] if host_ck else None
        for g in enc.groups:
            if snaps is not None and g.index % CKPT_CHUNK == 0:
                snaps.append(ffd.snapshot_state(st))
            if ts is not None and tenc.has_topo[g.index]:
                take, rem, runs = fill_group_topo(st, enc, tenc, ts, g.index)
                run_log[g.index] = runs
            else:
                take, rem = ffd.fill_group_closed_form(st, enc, g.index)
                if ts is not None:
                    record_plain_fill(tenc, ts, st, g.index, take)
            takes[g.index] = take
            leftover[g.index] = rem
        if snaps is not None:
            self._host_bank = dict(
                enc=enc, E=st.E, n_max=self.n_max,
                token=self._delta.state_token(), st=st, snaps=snaps,
                takes=takes, leftover=leftover)
            if self.metrics is not None:
                self.metrics.inc("karpenter_solver_solve_full_total",
                                 labels={"reason": hreason})
            final = dict(types=st.types.copy(), zones=st.zones.copy(),
                         ct=st.ct.copy(), pool=st.pool.copy(),
                         alive=st.alive.copy(), used=st.used.copy(),
                         E=st.E, run_log=run_log, zfix=None)
            return takes.copy(), leftover.copy(), final
        final = dict(types=st.types, zones=st.zones, ct=st.ct, pool=st.pool,
                     alive=st.alive, used=st.used, E=st.E,
                     run_log=run_log,
                     zfix=(ts.zfix if ts is not None else None))
        return takes, leftover, final

    def _dispatch(self, buf: np.ndarray, **statics) -> np.ndarray:
        """Run the packed solve buffer on the local device. The sidecar's
        RemoteSolver overrides this with a gRPC round trip — the solve
        itself is one buffer each way either way."""
        import jax.numpy as jnp

        from ..ops.ffd_jax import solve_scan_packed1
        from ..tenancy.compilecache import aot_kernel
        d_buf = jnp.asarray(buf)  # async enqueue; no sync before dispatch
        exe = aot_kernel("solve_scan_packed1", solve_scan_packed1,
                         d_buf, statics)
        if exe is not None:
            # primed AOT executable: zero tracing, zero XLA compile
            return np.asarray(exe(d_buf))
        # np.asarray is the only sync: it waits for exec + fetch at once
        return np.asarray(solve_scan_packed1(d_buf, **statics))

    def _dispatch_pruned(self, buf: np.ndarray, **statics) -> np.ndarray:
        """The pruned G-axis kernel (same wire + one trailing bail word).
        S arrives in ``statics`` from the _run_jax dispatch site — the
        single resolution point RemoteSolver shares. Local only —
        RemoteSolver disables it via supports_pruned_kernel."""
        import jax.numpy as jnp

        from ..ops.ffd_jax import solve_scan_packed1_pruned
        from ..tenancy.compilecache import aot_kernel
        d_buf = jnp.asarray(buf)
        exe = aot_kernel("solve_scan_packed1_pruned",
                         solve_scan_packed1_pruned, d_buf, statics)
        if exe is not None:
            return np.asarray(exe(d_buf))
        return np.asarray(solve_scan_packed1_pruned(d_buf, **statics))

    def _dispatch_many(self, bufs, **statics) -> np.ndarray:
        """Run B packed solve buffers in ONE device dispatch
        (ops/ffd_jax.py solve_scan_packed1_many = jit(vmap(body))):
        the scan carry batches over B, so B solves of the same shape
        bucket cost one sweep of scan trips plus one h2d/d2h round
        trip. On a multi-device engine the stacked [B, W] arena commits
        dp-sharded (parallel/mesh.py shard_batch) so the lanes land
        B/ndev per chip with zero cross-device collectives — lanes are
        independent, so results are byte-identical either way. The
        sidecar's RemoteSolver overrides this with the SolveBatch RPC —
        B buffers behind one batch frame, still one round trip
        (docs/solver-design.md "Over the wire")."""
        import jax.numpy as jnp

        from ..ops.ffd_jax import solve_scan_packed1_many
        from ..tenancy.compilecache import aot_kernel
        ndev = self._dev_devices()
        if ndev > 1:
            from ..parallel.mesh import shard_batch
            cache = self.__dict__.setdefault("_mesh_cache", {})
            d_bufs, B = shard_batch(np.stack(bufs), ndev, cache)
            return np.asarray(solve_scan_packed1_many(d_bufs, **statics))[:B]
        d_bufs = jnp.asarray(np.stack(bufs))
        exe = aot_kernel("solve_scan_packed1_many",
                         solve_scan_packed1_many, d_bufs, statics)
        if exe is not None:
            return np.asarray(exe(d_bufs))
        return np.asarray(solve_scan_packed1_many(d_bufs, **statics))

    @staticmethod
    def _fused_block_count(fuse: np.ndarray, Fu: int) -> int:
        """How many of the scan's Gp/Fu blocks take the vectorized
        branch: every group in the block after the first carries the
        same_run_as_prev flag."""
        return int(fuse.reshape(-1, Fu)[:, 1:].all(axis=1).sum())

    def _record_dispatch(self, kernel: str, batch: int, Gp: int, Fu: int,
                         fuse=None, fused_blocks: int = 0) -> None:
        """Evidence for the bench engine report (last_dispatch_stats):
        which kernel served, how many solves rode the dispatch, the scan
        trip count and the fused/sequential block split. For a batched
        dispatch fused_blocks is the per-lane average — vmap lowers the
        block cond to a select that runs both branches, so the split is
        shape evidence there, not a cost model."""
        steps = Gp // Fu if Fu > 1 else Gp
        if fuse is not None and Fu > 1:
            fused_blocks = self._fused_block_count(fuse, Fu)
        self.last_dispatch_stats = dict(
            kernel=kernel, batch=batch, fuse=Fu, scan_steps=steps,
            fused_blocks=fused_blocks, seq_blocks=steps - fused_blocks)

    # -- whole-fleet consolidation search ------------------------------
    #: the consolidation evaluator's subset search dispatches locally;
    #: the sidecar's RemoteSolver resolves this from the Info capability
    #: flag and routes through the SolveSubsets RPC instead
    supports_subset_kernel = True

    def arena_epoch(self):
        """Compound coherence token for identity-keyed caches derived
        from this solver's resident arenas (consolidation _base_tables):
        the incremental encoder's structural epoch (models/delta.py)
        PLUS the mesh resident arena's full-placement generation
        (parallel/mesh.py _place_resident). A mesh tick that re-placed
        the sharded arena from scratch is the same invalidation edge as
        a packed-buffer structural rebuild and must invalidate derived
        caches even when the delta epoch did not move
        (tests/test_consolidation_device.py regression)."""
        dep = self._delta.epoch if self._delta is not None else None
        mc = self.__dict__.get("_mesh_cache") or {}
        return (dep, mc.get("resident_gen", 0))

    def dispatch_subsets(self, arrays: dict, *, tprice, gid, n, dead,
                         keep, removed_price, n_max: int, E: int,
                         P: int) -> np.ndarray:
        """Run one whole-fleet consolidation subset batch on the device:
        the shared union-arena tensors (one _prep_device_inputs arena for
        the whole round) plus per-lane index/mask stacks, ONE dispatch
        for every lane (ops/consolidation_jax.subset_solve_kernel). On a
        multi-device engine the lane stacks commit dp-sharded
        (parallel/mesh.py shard_lanes) with the union arena replicated —
        lanes are independent, so results are byte-identical to the
        single-device kernel. Returns the [B, 5] SUBSET_OUT_COLS
        summary. The sidecar's RemoteSolver overrides this with the
        SolveSubsets RPC."""
        import jax.numpy as jnp

        from ..ops.consolidation_jax import subset_solve_kernel
        lanes = dict(gid=gid, n=n, dead=dead, keep=keep,
                     removed_price=removed_price)
        B = int(np.asarray(gid).shape[0])
        ndev = self._dev_devices()
        if ndev > 1:
            from ..parallel.mesh import shard_lanes
            cache = self.__dict__.setdefault("_mesh_cache", {})
            lanes, B = shard_lanes(lanes, ndev, cache)
        else:
            lanes = {k: jnp.asarray(np.asarray(v))
                     for k, v in lanes.items()}
        out = np.asarray(subset_solve_kernel(
            jnp.asarray(arrays["A"]), jnp.asarray(arrays["avail_zc"]),
            jnp.asarray(np.asarray(tprice)),
            jnp.asarray(arrays["R"]), jnp.asarray(arrays["n"]),
            jnp.asarray(arrays["F"]), jnp.asarray(arrays["agz"]),
            jnp.asarray(arrays["agc"]), jnp.asarray(arrays["admit"]),
            jnp.asarray(arrays["daemon"]),
            jnp.asarray(arrays["ex_compat"]),
            jnp.asarray(arrays["pool_types"]),
            jnp.asarray(arrays["pool_agz"]),
            jnp.asarray(arrays["pool_agc"]),
            jnp.asarray(arrays["pool_limit"]),
            jnp.asarray(arrays["pool_used0"]),
            jnp.asarray(arrays["ex_alloc"]),
            jnp.asarray(arrays["ex_used0"]),
            lanes["gid"], lanes["n"], lanes["dead"], lanes["keep"],
            lanes["removed_price"],
            n_max=n_max, E=E, P=P))[:B]
        self._record_dispatch(kernel="subset", batch=B,
                              Gp=int(np.asarray(gid).shape[1]), Fu=1)
        return out

    # -- preemption victim-set search ----------------------------------
    #: the preemption planner's lane batch dispatches locally; the
    #: sidecar's RemoteSolver turns this off (no Preempt RPC — the
    #: planner's numpy twin is bit-identical by contract)
    supports_preempt_kernel = True

    def dispatch_preempt(self, *, ex_alloc, ex_used, ex_compat, R, n,
                         freed) -> np.ndarray:
        """Run one preemption victim-set batch on the device: shared
        demand/node tables plus the per-lane ``freed`` refund stack, ONE
        dispatch for every candidate prefix
        (scheduling/preempt_jax.preempt_solve_kernel). Returns the [B]
        leftover-demand vector the planner picks its prefix from."""
        import jax.numpy as jnp

        from ..scheduling.preempt_jax import preempt_solve_kernel
        out = np.asarray(preempt_solve_kernel(
            jnp.asarray(np.asarray(ex_alloc)),
            jnp.asarray(np.asarray(ex_used)),
            jnp.asarray(np.asarray(ex_compat)),
            jnp.asarray(np.asarray(R)), jnp.asarray(np.asarray(n)),
            jnp.asarray(np.asarray(freed))))
        self._record_dispatch(kernel="preempt",
                              batch=int(np.asarray(freed).shape[0]),
                              Gp=int(np.asarray(R).shape[0]), Fu=1)
        return out

    # -- batched multi-solve -------------------------------------------
    #: solve_batch's vmapped dispatch runs the kernel locally; the
    #: sidecar's RemoteSolver turns this off (one buffer per RPC)
    supports_batch_kernel = True

    def solve_batch(self, snapshots) -> List[SolveResult]:
        """Solve many independent snapshots, batching eligible ones B per
        device dispatch (_dispatch_many). Decisions are EXACTLY
        ``[self.solve(s) for s in snapshots]`` — ineligible items
        (preference chains, topology terms, host-only shapes, minValues
        floors, group counts past the base-kernel cap, a busy or absent
        device engine) and items whose solve outgrows the slot bucket
        transparently take the single-solve path. Intended for
        consolidation's candidate pre-screen and the sidecar's queued
        solves, where many snapshots are in hand at once."""
        snapshots = list(snapshots)
        results: List[Optional[SolveResult]] = [None] * len(snapshots)
        buckets: Dict[Tuple, List] = {}
        for i, snap in enumerate(snapshots):
            item = self._prep_batch_item(snap)
            if item is None:
                results[i] = self.solve(snap)
            else:
                key = tuple(sorted(item["statics"].items()))
                buckets.setdefault(key, []).append((i, item))
        for key, items in buckets.items():
            if len(items) < 2:
                # nothing to amortize: the single path also keeps its
                # n-bucket overflow retry
                for i, _ in items:
                    results[i] = self.solve(snapshots[i])
                continue
            statics = dict(key)
            n_bucket = self._bucket
            # batched dispatches get their OWN router bucket (the
            # single-solve EWMAs must never absorb amortized-per-item
            # timings — backend='auto' single solves would mis-route)
            bkey = self._bucket_key(items[0][1]["enc"],
                                    items[0][1]["E"]) + ("batch",)
            import time as _time
            _t0 = _time.perf_counter()
            try:
                o = self._dispatch_many([it["buf"] for _, it in items],
                                        n_max=n_bucket, **statics)
            except DeviceDispatchFailed as e:
                # per-caller degradation: the batch died as ONE wire
                # attempt (RemoteSolver) or one local dispatch; every
                # item re-solves singly — each lands on its host twin
                # independently, none crashes its caller
                import logging
                logging.getLogger(__name__).warning(
                    "batched dispatch failed (%s); re-solving %d items "
                    "on the single path", e, len(items))
                self._router.observe(bkey, "dev", DEV_FAILED_MS)
                for i, _ in items:
                    results[i] = self.solve(snapshots[i])
                continue
            self._router.observe(
                bkey, "dev",
                (_time.perf_counter() - _t0) * 1e3 / len(items))
            fb = [it["fused_blocks"] for _, it in items]
            self._record_dispatch(
                kernel=("fused" if statics["F"] > 1 else "base"),
                batch=len(items), Gp=statics["G"], Fu=statics["F"],
                fused_blocks=round(sum(fb) / len(fb)))
            for (i, it), o_buf in zip(items, o):
                res = self._finish_batch_item(it, o_buf, statics,
                                              n_bucket)
                # slot-bucket overflow: the single path re-solves with
                # its 4x retry loop (and slot growth), identically
                results[i] = res if res is not None \
                    else self.solve(snapshots[i])
        return results

    def _prep_batch_item(self, snapshot: SchedulingSnapshot):
        """Encode one snapshot for the batched dispatch, or None when it
        must take the single-solve path. The gates mirror _solve_core's
        plain device branch plus the preference wrapper's no-preference
        short-circuit (solver/preferences.py), so a batched decision is
        the single path's decision by construction."""
        if self.backend == "numpy" or not self.supports_batch_kernel:
            return None
        if not snapshot.pods:
            return None
        from .route import dev_engine_usable
        if not dev_engine_usable(self._router):
            return None
        from ..models.encoding import canonical_pod_groups
        from .preferences import preference_count
        groups = canonical_pod_groups(snapshot.pods)
        if any(preference_count(plist[0]) for _sig, plist in groups):
            return None  # relax rounds re-solve: single path owns them
        enc = encode_snapshot(snapshot, pod_groups=groups)
        if enc.topo_any or not enc.types or enc.mv_K:
            return None
        existing = sorted(snapshot.existing_nodes, key=lambda n: n.name)
        if self.backend == "auto":
            # honor measured cost. Batched dispatches learn their OWN
            # bucket (amortized per-item ms, keyed + ("batch",)): when
            # it has evidence, compare the single-solve HOST cost
            # against the BATCHED dev cost — a shape where the host
            # beats a solo dispatch may still lose to an amortized one.
            # Without batched evidence, fall back to the single bucket's
            # verdict: a measured host win (the CPU no-win case of
            # docs/solver-design.md) stays host-routed, never pessimized
            snap_st = self._router.snapshot()
            skey = self._bucket_key(enc, len(existing))
            st = snap_st.get(skey)
            bst = snap_st.get(skey + ("batch",))
            host = st["host"] if st else None
            bdev = bst.get("dev") if bst else None
            if bdev is not None and bdev < DEV_FAILED_MS and host is not None:
                if host <= bdev:
                    return None
            elif (st and host is not None and st["dev"] is not None
                    and host <= st["dev"]):
                # a parked batch bucket (dispatch died) falls through to
                # the single bucket's verdict here — dev_engine_usable
                # above already keeps a dead link out, so recovery
                # re-measures instead of parking batching forever
                return None
        ex_alloc, ex_used, ex_compat = self._encode_existing(
            enc, existing)
        arrays, stt = self._prep_device_inputs(enc, ex_alloc, ex_used,
                                               ex_compat, 1)
        if stt["G"] > self.dev_max_groups:
            return None  # the pruned kernel doesn't batch
        from ..ops.hostpack import pack_inputs1
        buf = pack_inputs1(arrays, stt["T"], stt["D"], stt["Z"],
                           stt["C"], stt["G"], stt["E"], stt["P"],
                           stt["K"], stt["M"], stt["F"], stt["Q"])
        fb = 0
        if stt["F"] > 1:
            fb = self._fused_block_count(arrays["fuse"], stt["F"])
        return dict(enc=enc, existing=existing, buf=buf, statics=stt,
                    D=enc.A.shape[1], E=ex_alloc.shape[0],
                    fused_blocks=fb)

    def _finish_batch_item(self, it, o_buf, statics, n_bucket):
        """Unpack one slice of the batched output; None when the item
        exhausted the slot bucket (caller re-solves on the single path).
        The tail mirrors _run_jax's unpadding exactly."""
        from ..ops.hostpack import unpack_outputs1
        enc = it["enc"]
        G, E, D = len(enc.groups), it["E"], it["D"]
        Gp, Ep = statics["G"], statics["E"]
        out = unpack_outputs1(np.ascontiguousarray(o_buf), statics["T"],
                              statics["D"], statics["Z"], statics["C"],
                              Gp, Ep, statics["P"], n_bucket)
        if (out["leftover"].sum() > 0
                and int(out["num_nodes"][0]) >= n_bucket):
            return None
        takes = out["takes"][:G]
        takes = np.concatenate([takes[:, :E], takes[:, Ep:]], axis=1)
        sm = _slotmap(E, Ep, Ep + n_bucket)
        final = dict(
            types=out["types"][sm], zones=out["zones"][sm],
            ct=out["ct"][sm], pool=out["pool"][sm],
            alive=out["alive"][sm], used=out["used"][sm][:, :D],
            E=E)
        return self._decode(enc, it["existing"], takes,
                            out["leftover"][:G], final)

    def _dev_devices(self) -> int:
        """Device count of the dev engine (nonblocking, probed). >1 routes
        the type-parallel mesh solve; the sidecar's RemoteSolver pins this
        to 1 — its SERVER makes the mesh decision for its own devices."""
        from .route import dev_device_count
        return dev_device_count()

    def _dispatch_mesh(self, arrays: dict, *, T, D, Z, C, G, E, P, K, V, M,
                       n_max: int, ndev: int, dirty=None) -> dict:
        """The multi-device solve: catalog/candidate tensors sharded over
        the type axis (and node-slot state over a second dp axis when the
        device count factors and there are no minValues floors), carry
        replicated, collectives across the mesh (parallel/mesh.py
        dispatch_mesh — shared with the sidecar server). ``dirty`` is the
        pack cache's field-level delta claim: a list keeps the sharded
        arena resident and re-places only those fields; None re-places
        everything. Same outputs as unpack_outputs1."""
        from ..parallel.mesh import dispatch_mesh
        cache = self.__dict__.setdefault("_mesh_cache", {})
        return dispatch_mesh(arrays, n_max=n_max, E=E, P=P, V=V,
                             ndev=ndev, cache=cache, dirty=dirty,
                             metrics=self.metrics)

    # -- topology device path ------------------------------------------
    #: static event-loop bounds of the device pour (ops/topo_jax.py);
    #: snapshots that exceed them bail back to the host engine
    TOPO_EVCAP = 128
    TOPO_PMAX = 8

    def _topo_lowerable(self, enc, tenc, existing) -> bool:
        """Conservative device-pour envelope (ops/topo_jax.py scope): no
        existing nodes, no minValues floors, and no duplicate counter
        references inside one group's constraint lists (the dense kernel
        rows merge duplicates, which would change the zone-choice score
        the host computes per-constraint)."""
        if existing:
            return False
        if enc.mv_floor is not None and enc.mv_floor.any():
            return False
        for g in enc.groups:
            gi = g.index
            for lst in (tenc.zspread[gi], tenc.hspread[gi],
                        tenc.zaff[gi], tenc.haff[gi]):
                ids = [e[0] for e in lst]
                if len(ids) != len(set(ids)):
                    return False
        return True

    def _topo_rows(self, enc, tenc):
        """Densify TopoEncoding into ops/topo_jax.TopoGroupRows arrays
        (numpy, padded to the group bucket by the caller)."""
        G = len(enc.groups)
        Z = len(enc.zones)
        GZ = max(1, tenc.GZ)
        GH = max(1, tenc.GH)
        big = np.int64(1) << 60
        rows = dict(
            has_topo=np.array(tenc.has_topo, dtype=bool),
            zone_needed=np.array(tenc.zone_needed, dtype=bool),
            min_mask=np.asarray(tenc.min_mask, dtype=bool),
            zs_any=np.zeros((G, GZ), bool),
            zs_skew=np.full((G, GZ), big, np.int64),
            hs_any=np.zeros((G, GH), bool),
            hs_skew=np.full((G, GH), big, np.int64),
            za_any=np.zeros((G, GZ), bool),
            za_anti=np.zeros((G, GZ), bool),
            za_own=np.zeros((G, GZ), bool),
            ha_any=np.zeros((G, GH), bool),
            ha_anti=np.zeros((G, GH), bool),
            ha_own=np.zeros((G, GH), bool),
            member_z=np.full(G, -1, np.int32),
            member_h=np.full(G, -1, np.int32),
        )
        for g in range(G):
            for gz, s, enforce in tenc.zspread[g]:
                rows["zs_any"][g, gz] = True
                if enforce:
                    rows["zs_skew"][g, gz] = min(rows["zs_skew"][g, gz], s)
            for gh, s, enforce in tenc.hspread[g]:
                rows["hs_any"][g, gh] = True
                if enforce:
                    rows["hs_skew"][g, gh] = min(rows["hs_skew"][g, gh], s)
            for gz, anti, own in tenc.zaff[g]:
                rows["za_any"][g, gz] = True
                rows["za_anti"][g, gz] = anti
                rows["za_own"][g, gz] = own
            for gh, anti, own in tenc.haff[g]:
                rows["ha_any"][g, gh] = True
                rows["ha_anti"][g, gh] = anti
                rows["ha_own"][g, gh] = own
            # membership counters not already covered by the spread rows
            # (ops/topo.py:_record's seen_z/seen_h dedup)
            mz = tenc.member_z[g]
            if mz >= 0 and not rows["zs_any"][g, mz]:
                rows["member_z"][g] = mz
            mh = tenc.member_h[g]
            if mh >= 0 and not rows["hs_any"][g, mh]:
                rows["member_h"][g] = mh
        return rows, GZ, GH

    def _dispatch_topo(self, arrays: dict, rows: dict, statics: dict,
                       cache: dict = None) -> dict:
        """Run the topology event kernel locally (the sidecar's
        RemoteSolver overrides this with a SolveTopo gRPC round trip —
        ops/topo_jax.dispatch_topo is the shared implementation both
        ends run)."""
        from ..ops.topo_jax import dispatch_topo
        return dispatch_topo(arrays, rows, statics, cache=cache)

    def _patch_topo_cache(self, tc, enc, d) -> List[str]:
        """Rows-tier patch of the resident topo base arrays (the analog
        of _patch_pack_cache for the topology pour). The topo device
        path always runs with E == 0, so of the delta's dirty-field
        vocabulary only pod counts and pool tables can apply; the
        zero-width ex tables are inert by construction. Returns the
        patched field names so the caller can refresh exactly those
        fields of the resident device placement."""
        arrays = tc["arrays"]
        G = len(enc.groups)
        D = len(enc.dims)
        dirty64, dirtyb = d.dirty_fields()
        fields = [f for f in dirty64 + dirtyb
                  if f in ("n", "pool_limit", "pool_used0")]
        if "n" in fields:
            arrays["n"][:G] = enc.n
        if "pool_limit" in fields:
            pl, pu = arrays["pool_limit"], arrays["pool_used0"]
            for p in enc.pools:
                lim = p.limit_vec if p.limit_vec is not None \
                    else np.full(D, -1, dtype=np.int64)
                pl[p.index, :D] = lim
                pl[p.index, D:] = -1
                pu[p.index, :D] = p.in_use_vec
        return fields

    def _run_jax_topo(self, enc, tenc):
        """The device pour: same decisions as _run_numpy's topology path,
        served by ops/topo_jax.solve_scan_topo via _dispatch_topo.
        Raises TopoKernelBail when the snapshot leaves the kernel's
        event envelope.

        Residency: the base arrays (pool tables + padded group rows) and
        their device placement persist across ticks in ``_topo_cache``
        under the same staleness rules as _run_jax's pack cache (same
        encoding object, hit/rows tier, version lag <= 1); a rows-tier
        tick patches only the dirty fields host-side and re-places just
        those fields on device. The topology rows (skews, membership)
        derive from ``tenc``, which is rebuilt per snapshot — they are
        re-placed on any non-hit tick and only the device copy is reused
        on a quiet (hit-tier) tick."""
        T, D = enc.A.shape
        Z, C = len(enc.zones), enc.avail.shape[2]
        P = len(enc.pools)
        G = len(enc.groups)
        Gp = max(1, 1 << (G - 1).bit_length())
        Pp = max(1, 1 << (P - 1).bit_length())
        Dp = max(8, D)

        d = self._last_delta
        dver = self._delta.version if self._delta is not None else None
        tc = getattr(self, "_topo_cache", None)
        arrays = None
        conv_cache: dict = {}
        if (tc is not None and d is not None and dver is not None
                and d.tier in ("hit", "rows") and tc["enc"] is enc
                and tc["stt"] == (T, Z, C, Gp, Pp, Dp)
                and tc["version"] in (dver, dver - 1)):
            arrays = tc["arrays"]
            conv_cache = tc["conv"]
            if tc["version"] != dver:
                fields = self._patch_topo_cache(tc, enc, d)
                if fields and "inp" in conv_cache:
                    import jax.numpy as jnp
                    conv_cache["inp"] = conv_cache["inp"]._replace(
                        **{f: jnp.asarray(arrays[f]) for f in fields})
                tc["version"] = dver
                tc["mode"] = "patch"
                tc["fields"] = fields
            else:
                tc["mode"] = "reuse"
                tc["fields"] = []
            if d.tier != "hit":
                # tenc-derived rows may have moved: force a fresh device
                # placement of the rows block (base inputs stay resident)
                conv_cache.pop("rows", None)

        def padG(a):
            return np.pad(a, [(0, Gp - G)] + [(0, 0)] * (a.ndim - 1))

        def padD(a):
            return np.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, Dp - D)])

        if arrays is None:
            arrays = dict(
                A=padD(enc.A),
                avail_zc=enc.avail.reshape(T, Z * C),
                R=padG(padD(enc.R)), n=padG(enc.n), F=padG(enc.F),
                agz=padG(enc.agz), agc=padG(enc.agc),
                admit=np.pad(padG(enc.admit), [(0, 0), (0, Pp - P)]),
                daemon=np.pad(padG(padD(enc.daemon)),
                              [(0, 0), (0, Pp - P), (0, 0)]),
                ex_alloc=np.zeros((0, Dp), np.int64),
                ex_used0=np.zeros((0, Dp), np.int64),
                ex_compat=np.zeros((Gp, 0), bool),
            )
            pool_types = np.zeros((Pp, T), bool)
            pool_agz = np.zeros((Pp, Z), bool)
            pool_agc = np.zeros((Pp, C), bool)
            pool_limit = np.zeros((Pp, Dp), np.int64)
            pool_used0 = np.zeros((Pp, Dp), np.int64)
            for p in enc.pools:
                pool_types[p.index] = p.type_rows
                pool_agz[p.index] = p.agz
                pool_agc[p.index] = p.agc
                lim = p.limit_vec if p.limit_vec is not None \
                    else np.full(D, -1, dtype=np.int64)
                pool_limit[p.index, :D] = lim
                pool_limit[p.index, D:] = -1
                pool_used0[p.index, :D] = p.in_use_vec
            arrays.update(pool_types=pool_types, pool_agz=pool_agz,
                          pool_agc=pool_agc, pool_limit=pool_limit,
                          pool_used0=pool_used0)
            conv_cache = {}
            if dver is not None:
                self._topo_cache = dict(
                    enc=enc, arrays=arrays, stt=(T, Z, C, Gp, Pp, Dp),
                    conv=conv_cache, version=dver, mode="full",
                    fields=None)
            else:
                self._topo_cache = None

        rows, GZ, GH = self._topo_rows(enc, tenc)
        GZp = max(1, 1 << (GZ - 1).bit_length())
        GHp = max(1, 1 << (GH - 1).bit_length())
        big = np.int64(1) << 60

        def padC(a, width, fill):
            out = np.full((Gp, width), fill, a.dtype)
            out[:G, :a.shape[1]] = a
            return out

        topo_rows = dict(
            has_topo=np.pad(rows["has_topo"], (0, Gp - G)),
            zone_needed=np.pad(rows["zone_needed"], (0, Gp - G)),
            min_mask=padG(rows["min_mask"]),
            zs_any=padC(rows["zs_any"], GZp, False),
            zs_skew=padC(rows["zs_skew"], GZp, big),
            hs_any=padC(rows["hs_any"], GHp, False),
            hs_skew=padC(rows["hs_skew"], GHp, big),
            za_any=padC(rows["za_any"], GZp, False),
            za_anti=padC(rows["za_anti"], GZp, False),
            za_own=padC(rows["za_own"], GZp, False),
            ha_any=padC(rows["ha_any"], GHp, False),
            ha_anti=padC(rows["ha_anti"], GHp, False),
            ha_own=padC(rows["ha_own"], GHp, False),
            member_z=np.pad(rows["member_z"], (0, Gp - G),
                            constant_values=-1),
            member_h=np.pad(rows["member_h"], (0, Gp - G),
                            constant_values=-1),
        )
        n_bucket = self._bucket
        while True:
            out = self._dispatch_topo(arrays, topo_rows, dict(
                Z=Z, P=Pp, GZ=GZp, GH=GHp, n_max=n_bucket,
                EVCAP=self.TOPO_EVCAP, PMAX=self.TOPO_PMAX),
                cache=conv_cache)
            # materialize only the retry-decision scalars; the full
            # output set transfers once, after the loop settles
            bail = np.asarray(out["bail"])
            leftover = np.asarray(out["leftover"])
            nn = int(np.asarray(out["num_nodes"])[0])
            if bail.any():
                raise TopoKernelBail(
                    f"{int(bail.sum())} group(s) exceeded the "
                    f"{self.TOPO_EVCAP}-event device envelope")
            exhausted = leftover.sum() > 0 and nn >= n_bucket
            if not exhausted or n_bucket >= self.n_max:
                break
            n_bucket = min(n_bucket * 4, self.n_max)
        self._bucket = self._settle_bucket(n_bucket, nn)
        out = {k: np.asarray(v) for k, v in out.items()}
        takes = out["takes"]
        leftover = out["leftover"]

        ev = {k[3:]: v for k, v in out.items() if k.startswith("ev_")}
        run_log = {}
        for g in enc.groups:
            gi = g.index
            if rows["has_topo"][gi]:
                run_log[gi] = _runs_from_events(ev, gi)
        final = dict(
            types=out["types"], zones=out["zones"], ct=out["ct"],
            pool=out["pool"], alive=out["alive"],
            used=out["used"][:, :D],
            E=0, run_log=run_log, zfix=out["zfix"])
        return takes[:G], leftover[:G], final

    def _prep_device_inputs(self, enc, ex_alloc, ex_used, ex_compat,
                            ndev: int):
        """Pad one snapshot's encoding into the kernel's shape buckets
        and resolve its fused-scan plan. Returns ``(arrays, statics)``
        where statics carries every pack/dispatch static EXCEPT n_max
        (the caller's retry loop resolves the slot bucket per dispatch).
        Shared by the single-solve path (_run_jax) and the batched
        multi-solve (solve_batch) so the two can never pad — and hence
        decide — differently."""
        T, D = enc.A.shape
        Z, C = len(enc.zones), enc.avail.shape[2]
        P = len(enc.pools)
        E = ex_alloc.shape[0]
        # --- shape bucketing: avoid a fresh XLA compile per snapshot -----
        # G -> next pow2 (padded groups have n=0: provably no-op steps);
        # E/P -> pow2 buckets (padded existing rows are dead, padded pools
        # admit nothing); D -> 8.
        G = len(enc.groups)
        Gp = max(1, 1 << (G - 1).bit_length())
        Ep = 1 << (E - 1).bit_length() if E else 0
        Pp = max(1, 1 << (P - 1).bit_length())
        Dp = max(8, D)

        def padG(a):
            return np.pad(a, [(0, Gp - G)] + [(0, 0)] * (a.ndim - 1))

        def padD(a):
            return np.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, Dp - D)])

        arrays = dict(
            A=padD(enc.A),
            avail_zc=enc.avail.reshape(T, Z * C),
            R=padG(padD(enc.R)), n=padG(enc.n), F=padG(enc.F),
            agz=padG(enc.agz), agc=padG(enc.agc),
            admit=np.pad(padG(enc.admit), [(0, 0), (0, Pp - P)]),
            daemon=np.pad(padG(padD(enc.daemon)),
                          [(0, 0), (0, Pp - P), (0, 0)]),
        )
        pool_types = np.zeros((Pp, T), bool)
        pool_agz = np.zeros((Pp, Z), bool)
        pool_agc = np.zeros((Pp, C), bool)
        pool_limit = np.zeros((Pp, Dp), np.int64)  # limit 0 => padded pools inert
        pool_used0 = np.zeros((Pp, Dp), np.int64)
        for p in enc.pools:
            pool_types[p.index] = p.type_rows
            pool_agz[p.index] = p.agz
            pool_agc[p.index] = p.agc
            lim = p.limit_vec if p.limit_vec is not None \
                else np.full(D, -1, dtype=np.int64)
            pool_limit[p.index, :D] = lim
            pool_limit[p.index, D:] = -1
            pool_used0[p.index, :D] = p.in_use_vec
        arrays.update(pool_types=pool_types, pool_agz=pool_agz,
                      pool_agc=pool_agc, pool_limit=pool_limit,
                      pool_used0=pool_used0)
        ex_alloc_p = np.zeros((Ep, Dp), np.int64)
        ex_used_p = np.zeros((Ep, Dp), np.int64)
        ex_compat_p = np.zeros((Gp, Ep), bool)
        if E:
            ex_alloc_p[:E, :D] = ex_alloc
            ex_used_p[:E, :D] = ex_used
            # dead padded rows: zero allocatable, incompatible with everyone
            ex_compat_p[:G, :E] = ex_compat
        arrays.update(ex_alloc=ex_alloc_p, ex_used0=ex_used_p,
                      ex_compat=ex_compat_p)

        # minValues floors (padded pools have zero floors — inert)
        K, V, M = enc.mv_K, enc.mv_V, enc.mv_M
        if K:
            mv_floor_p = np.zeros((Pp, K), np.int64)
            mv_floor_p[:P] = enc.mv_floor
            arrays.update(mv_floor=mv_floor_p, mv_pairs_t=enc.mv_pairs_t,
                          mv_pairs_v=enc.mv_pairs_v)

        # priority vector (Q=1 gates the arena section; padded groups
        # are inert at priority 0). Single-device only: the mesh path
        # stays Q-free — decisions are priority-blind (canonical order
        # encodes priority), so stripping the section changes nothing,
        # and the sharded resident-arena walk keeps its layout.
        Q = 0
        if enc.prio is not None and ndev <= 1:
            Q = 1
            arrays["prio"] = padG(enc.prio)

        # --- fused-scan plan (ops/ffd_jax.py _solve_fused) ---------------
        # groups the encoder proves pairwise disjoint on BOTH contention
        # axes — admitted pools and compatible existing nodes — fill in
        # one scan step, Fu at a time. ANDing the two separate run walks
        # is valid: a block inside one combined run is pairwise disjoint
        # in each dimension. Gates mirror use_pruned's shape envelope:
        # the mesh and pruned kernels keep their own scan shapes.
        Fu = 1
        if (ndev <= 1 and K == 0 and self.dev_fuse > 1
                and Gp <= self.dev_max_groups
                and Gp >= self.dev_fuse_min_groups):
            from ..models.encoding import independent_runs
            fuse = enc.fused_runs().copy()
            if E:
                fuse &= independent_runs(ex_compat)
            # padded groups (n=0, all-False rows) are provable no-op
            # steps: fusable with anything
            fuse = np.concatenate([fuse, np.ones(Gp - G, dtype=bool)])
            arrays["fuse"] = fuse
            Fu = min(self.dev_fuse, Gp)  # both pow2, so Fu divides Gp
        return arrays, dict(T=T, D=Dp, Z=Z, C=C, G=Gp, E=Ep, P=Pp,
                            K=K, V=V, M=M, F=Fu, Q=Q)

    def _patch_pack_cache(self, pc, enc, ex_alloc, ex_used, ex_compat,
                          d) -> List[str]:
        """Bring the resident padded arrays + packed arena up to the
        current delta: re-pad only the dirty fields (the delta's
        dirty_fields() vocabulary) and, when a packed wire buffer is
        resident (single-device entries), patch its dirty sections in
        place (ops/hostpack.py patch_inputs1). Mesh entries keep
        arrays-only residency (buf=None) — the returned dirty-field list
        drives the sharded device-arena patch instead (parallel/mesh.py
        _place_resident). Only fields a ``rows``-tier delta can move are
        handled — every signature/structure-derived field is untouched
        by contract. Byte-parity with a fresh pack is fuzzed in
        tests/test_delta_encoding.py."""
        from ..ops.hostpack import patch_inputs1
        arrays, stt = pc["arrays"], pc["stt"]
        T, Dp, Z, C = stt["T"], stt["D"], stt["Z"], stt["C"]
        Gp, Ep, Pp = stt["G"], stt["E"], stt["P"]
        K, M, Fu = stt["K"], stt["M"], stt["F"]
        Q = stt.get("Q", 0)
        D = len(enc.dims)
        G, E = len(enc.groups), ex_alloc.shape[0]
        dirty64, dirtyb = d.dirty_fields()
        if "prio" in dirty64:
            # defensive: a rows-tier delta provably cannot move prio
            # (priority is part of the signature), but the vocabulary
            # covers it so a future tier that does is patched, not
            # silently stale
            if Q and enc.prio is not None:
                arrays["prio"][:G] = enc.prio
            else:
                dirty64 = [f for f in dirty64 if f != "prio"]
        if "n" in dirty64:
            arrays["n"][:G] = enc.n
        if "pool_limit" in dirty64:
            pl, pu = arrays["pool_limit"], arrays["pool_used0"]
            for p in enc.pools:
                lim = p.limit_vec if p.limit_vec is not None \
                    else np.full(D, -1, dtype=np.int64)
                pl[p.index, :D] = lim
                pl[p.index, D:] = -1
                pu[p.index, :D] = p.in_use_vec
        if "ex_alloc" in dirty64:
            ap, up = arrays["ex_alloc"], arrays["ex_used0"]
            ap[:] = 0
            up[:] = 0
            if E:
                ap[:E, :D] = ex_alloc
                up[:E, :D] = ex_used
        if "ex_compat" in dirtyb:
            cp = arrays["ex_compat"]
            cp[:] = False
            if E:
                cp[:G, :E] = ex_compat
            if "fuse" in arrays:
                # the fused-scan plan ANDs the admit runs (unchanged in
                # a rows-tier delta) with the existing-compat runs —
                # recompute exactly as _prep_device_inputs does
                from ..models.encoding import independent_runs
                fuse = enc.fused_runs().copy()
                if E:
                    fuse &= independent_runs(ex_compat)
                arrays["fuse"][:] = np.concatenate(
                    [fuse, np.ones(Gp - G, dtype=bool)])
                dirtyb.append("fuse")
        spans = []
        if (dirty64 or dirtyb) and pc["buf"] is not None:
            spans = patch_inputs1(pc["buf"], pc["bflat"], arrays, dirty64,
                                  dirtyb, T, Dp, Z, C, Gp, Ep, Pp, K, M,
                                  Fu, Q)
        # the (start, stop) word sections just overwritten — the delta
        # wire's payload source: the RemoteSolver ships exactly these
        # sections over SolvePatch instead of the whole arena
        pc["spans"] = spans
        return dirty64 + dirtyb

    def _arena_for(self, enc, ex_alloc, ex_used, ex_compat, ndev):
        """Resident packed arena (patched-arena wire path), extracted
        from _run_jax so the pipelined tick's prepare stage shares it.
        When the delta tier proves the shape class unchanged (same
        resident encoding object, same padded E bucket), the previous
        solve's padded arrays + packed buffer are reused: clean solves
        ship the very same buffer (the RemoteSolver then re-sends it
        without re-packing), dirty ones patch only the dirty sections
        (ops/hostpack.py patch_inputs1). Versioning guards host-served
        solves in between: a buffer lagging the encoder by more than
        one version is re-packed, never patched. Returns
        (arrays, stt, buf, mesh_dirty)."""
        from ..ops.hostpack import pack_inputs1_state
        E = ex_alloc.shape[0]
        d = self._last_delta
        dver = self._delta.version if self._delta is not None else None
        pc = self._pack_cache
        arrays = stt = buf = None
        mesh_dirty = None  # advisory for the mesh resident arena
        if (pc is not None and d is not None and dver is not None
                and d.tier in ("hit", "rows")
                and pc["enc"] is enc and pc["ndev"] == ndev
                and pc["stt"]["E"] == (1 << (E - 1).bit_length()
                                       if E else 0)
                and pc["version"] in (dver, dver - 1)):
            arrays, stt, buf = pc["arrays"], pc["stt"], pc["buf"]
            mesh_dirty = []
            if pc["version"] != dver:
                prev = pc["version"]
                mesh_dirty = self._patch_pack_cache(pc, enc, ex_alloc,
                                                    ex_used, ex_compat, d)
                pc["version"] = dver
                # the version transition these spans carry across —
                # the delta wire ships them only when the server's
                # resident copy sits exactly at `base`
                pc["sections"] = dict(base=prev, to=dver,
                                      spans=pc.pop("spans", []))
        if arrays is None:
            arrays, stt = self._prep_device_inputs(enc, ex_alloc, ex_used,
                                                   ex_compat, ndev)
        Gp = stt["G"]
        T, Dp, Z, C = stt["T"], stt["D"], stt["Z"], stt["C"]
        Ep, Pp = stt["E"], stt["P"]
        K, M, Fu = stt["K"], stt["M"], stt["F"]
        if buf is None and ndev <= 1:
            buf, bflat = pack_inputs1_state(arrays, T, Dp, Z, C, Gp, Ep,
                                            Pp, K, M, Fu, stt.get("Q", 0))
            if dver is not None:
                self._pack_cache = dict(enc=enc, arrays=arrays, stt=stt,
                                        buf=buf, bflat=bflat, ndev=ndev,
                                        version=dver, sections=None)
            else:
                self._pack_cache = None
        elif ndev > 1 and mesh_dirty is None:
            # mesh entries keep arrays-only residency: the wire buffer is
            # never packed (the sharded arena lives on-device, placed and
            # patched per shard by parallel/mesh.py _place_resident)
            if dver is not None:
                self._pack_cache = dict(enc=enc, arrays=arrays, stt=stt,
                                        buf=None, bflat=None, ndev=ndev,
                                        version=dver, sections=None)
            else:
                self._pack_cache = None
        return arrays, stt, buf, mesh_dirty

    # -- incremental solve (checkpointed scan prefix reuse) ------------
    def _bank_prev_token(self):
        """The ``(epoch, version)`` the resident arena held BEFORE this
        encode — the coherence edge a checkpoint bank must sit at to be
        restorable: the current delta describes exactly the transition
        from that token to now, so a bank recorded there plus this
        delta's frontier covers every byte that moved. A bank at any
        OTHER token (host-served ticks in between, version lag > 1,
        epoch bump) is stale by construction. None when incremental
        encoding is off or no delta classified this solve."""
        d, de = self._last_delta, self._delta
        if d is None or de is None:
            return None
        bumped = (d.n_dirty or d.pools_dirty or d.ex_rows_dirty
                  or d.ex_compat_dirty)
        return (de.epoch, de.version - (1 if bumped else 0))

    def _dispatch_ckpt(self, buf: np.ndarray, **statics):
        """Full solve that also emits the device-resident checkpoint
        bank (ops/ffd_jax.py solve_scan_packed1_ckpt). Local only —
        the RemoteSolver never calls it (supports_ckpt_kernel)."""
        from ..ops.ffd_jax import solve_scan_packed1_ckpt
        from ..tenancy.compilecache import aot_kernel
        exe = aot_kernel("solve_scan_packed1_ckpt", solve_scan_packed1_ckpt,
                         buf, statics)
        if exe is not None:
            o_buf, bank = exe(buf)
        else:
            o_buf, bank = solve_scan_packed1_ckpt(buf, **statics)
        return np.asarray(o_buf), bank

    def _dispatch_suffix(self, buf: np.ndarray, bank, **statics):
        """Suffix-only re-solve against the resident checkpoint bank
        (ops/ffd_jax.py solve_scan_suffix). Checkpoint select and bank
        splice happen inside the kernel, so a warm tick is ONE device
        dispatch; the bank pytree stays device-resident and only the
        packed arena and the suffix output cross host<->device. The
        arena goes in as the host ndarray — the jit's argument path
        transfers it several times cheaper than an eager asarray
        (measured ~20us vs ~100-300us per tick on CPU)."""
        from ..ops.ffd_jax import solve_scan_suffix
        from ..tenancy.compilecache import aot_kernel_n
        exe = aot_kernel_n("solve_scan_suffix", solve_scan_suffix,
                           (buf, bank), statics)
        if exe is not None:
            o_buf, new_bank = exe(buf, bank)
        else:
            o_buf, new_bank = solve_scan_suffix(buf, bank, **statics)
        return np.asarray(o_buf), new_bank

    @staticmethod
    def _ckpt_statics(stt: dict, n_bucket: int) -> dict:
        """The ckpt/suffix kernels' static set for this arena: the base
        statics minus the fused width F (the checkpointed scan is the
        unfused body — eligibility guarantees Fu == 1)."""
        return dict(T=stt["T"], D=stt["D"], Z=stt["Z"], C=stt["C"],
                    G=stt["G"], E=stt["E"], P=stt["P"], K=stt["K"],
                    V=stt["V"], M=stt["M"], Q=stt.get("Q", 0),
                    n_max=n_bucket)

    def _adopt_bank(self, buf, stt, n_bucket, bank, out, CK) -> None:
        """Install a freshly recorded checkpoint bank + the padded
        takes/leftover it solves for, stamped with the encoder token the
        arena now sits at, then pre-compile the suffix ladder so the
        first warm tick never traces."""
        from .incremental import live_bound
        gl = live_bound(buf, T=stt["T"], D=stt["D"], G=stt["G"], CK=CK)
        self._ckpt_bank = dict(
            key=(tuple(sorted(stt.items())), n_bucket), CK=CK, GL=gl,
            token=self._delta.state_token(), bank=bank,
            takes=out["takes"].copy(), leftover=out["leftover"].copy())
        self._prime_suffix(buf, stt, n_bucket, CK, gl)

    def _prime_suffix(self, buf, stt, n_bucket, CK, gl) -> None:
        """Compile every suffix bucket of this shape class ONCE, at
        bank-adoption time (the cold tick that already paid the full
        compile). The bucket ladder bounds this at O(log G) classes;
        results are discarded — only the traced executables matter.
        Keyed so repeat adoptions (every warm full solve) are free.

        Runs only while the AOT store is RECORDING (hack/aotprime.py):
        a serving replica preloads the recorded ladder, and one without
        a store compiles each bucket on its first warm tick — whereas
        eagerly compiling the ladder for EVERY adopted shape class
        would tax short-lived solvers (the test suite pays ~1 min of
        dead compiles across its many one-shot arena shapes)."""
        from ..tenancy.compilecache import aot_recording
        if not aot_recording():
            return
        key = (tuple(sorted(stt.items())), n_bucket, gl)
        if key in self._suffix_primed or gl <= 0:
            return
        from .incremental import suffix_buckets
        bank = self._ckpt_bank["bank"]
        statics = self._ckpt_statics(stt, n_bucket)
        for SUF in suffix_buckets(stt["G"], CK, GL=gl):
            self._dispatch_suffix(buf, bank, CK=CK, SUF=SUF, GL=gl,
                                  **statics)
        self._suffix_primed.add(key)

    def _try_suffix(self, buf, stt, n_bucket):
        """Serve this solve from the resident checkpoint bank if every
        validity edge holds. Returns ``(out, reason, info)``: ``out`` is
        the full unpacked result dict (suffix rows spliced over the
        resident takes/leftover, carry fields straight from the suffix —
        byte-identical to a from-scratch solve by the kernel parity
        contract) or None with ``reason`` naming the full-solve cause
        (the solve_full_total metric label)."""
        from ..ops.hostpack import unpack_outputs1
        from .incremental import live_bound, suffix_plan
        d = self._last_delta
        if d is None or self._delta is None:
            return None, "disabled", None
        if d.tier not in ("hit", "rows"):
            return None, "tier", None
        bk = self._ckpt_bank
        if bk is None:
            return None, "cold", None
        key = (tuple(sorted(stt.items())), n_bucket)
        if bk["key"] != key:
            return (None,
                    "bucket" if bk["key"][0] == key[0] else "shape",
                    None)
        tok = self._bank_prev_token()
        if bk["token"] != tok:
            return (None,
                    "epoch" if bk["token"][0] != tok[0] else
                    "version_lag", None)
        if d.dirty_frontier <= 0:
            return None, "frontier", None
        Gp, CK = stt["G"], bk["CK"]
        gl = live_bound(buf, T=stt["T"], D=stt["D"], G=Gp, CK=CK)
        if gl != bk["GL"] or gl <= 0:
            # the live bound moved under a rows tick (a tail group
            # emptied without a structural transition): the primed
            # suffix ladder no longer matches — re-record at the new
            # bound rather than scan a stale region
            return None, "shape", None
        jr, SUF = suffix_plan(min(d.dirty_frontier, Gp), Gp, CK, GL=gl)
        o_buf, new_bank = self._dispatch_suffix(
            buf, bk["bank"], CK=CK, SUF=SUF, GL=gl,
            **self._ckpt_statics(stt, n_bucket))
        sv = unpack_outputs1(o_buf, stt["T"], stt["D"], stt["Z"],
                             stt["C"], SUF * CK, stt["E"], stt["P"],
                             n_bucket)
        s0 = jr * CK
        bk["takes"][s0:gl] = sv["takes"]
        bk["leftover"][s0:gl] = sv["leftover"]
        # re-stamp: the kernel already spliced the suffix's entry
        # carries over the stale bank tail; adopt it and advance the
        # token — the bank tracks the arena without ever re-recording
        # the clean prefix
        bk["bank"] = new_bank
        bk["token"] = self._delta.state_token()
        out = dict(sv)
        out["takes"] = bk["takes"].copy()
        out["leftover"] = bk["leftover"].copy()
        self._solve_mode = f"suffix@{SUF}"
        return out, d.tier, dict(resume_group=s0, suffix_bucket=SUF,
                                 suffix_groups=SUF * CK)

    def _solve_counter(self, reason: str, sinfo=None) -> None:
        """Emit the suffix/full counters + depth histogram for a
        single-device base-path solve (the only path banks serve)."""
        m = self.metrics
        if m is None:
            return
        if sinfo is not None:
            m.inc("karpenter_solver_solve_suffix_total",
                  labels={"reason": reason})
            m.observe("karpenter_solver_solve_suffix_groups",
                      float(sinfo["suffix_groups"]))
        else:
            m.inc("karpenter_solver_solve_full_total",
                  labels={"reason": reason})

    def _run_jax(self, enc, ex_alloc, ex_used, ex_compat):
        from ..ops.hostpack import unpack_outputs1
        D = enc.A.shape[1]
        G, E = len(enc.groups), ex_alloc.shape[0]
        ndev = self._dev_devices()
        arrays, stt, buf, mesh_dirty = self._arena_for(
            enc, ex_alloc, ex_used, ex_compat, ndev)
        T, Dp, Z, C = stt["T"], stt["D"], stt["Z"], stt["C"]
        Gp, Ep, Pp = stt["G"], stt["E"], stt["P"]
        K, V, M, Fu = stt["K"], stt["V"], stt["M"], stt["F"]
        Q = stt.get("Q", 0)

        # --- bucketed new-node slots with overflow retry ------------------
        # Steady state needs far fewer than n_max slots; a small N keeps the
        # carry (and the d2h payload) small. If the solve exhausts every
        # slot with pods left over, rerun with 4x slots (decisions are
        # invariant to N once N is large enough: spare slots never fill).
        # beyond the base kernel's group cap the PRUNED kernel serves
        # (bound pass + S-slot exact; ops/ffd_jax.py) — eligible only
        # locally, single-device, without minValues floors or the
        # priority arena section (its body hardcodes the Q=0 layout)
        use_pruned = (self.supports_pruned_kernel and ndev <= 1
                      and K == 0 and Q == 0 and Gp > self.dev_max_groups)
        if Q and Gp > self.dev_max_groups:
            # priority-carrying arenas past the base cap: the host twin
            # serves (same decisions; the pruned buffer walk cannot
            # carry the Q section) — never silently
            import logging
            logging.getLogger(__name__).info(
                "padded group count %d exceeds the base kernel cap %d "
                "with a priority arena; serving from the host twin",
                Gp, self.dev_max_groups)
            if self.metrics is not None:
                self.metrics.inc(
                    "karpenter_solver_device_fallback_total",
                    labels={"reason": "group_cap"})
            return self._run_numpy(enc, ex_alloc, ex_used, ex_compat)
        if ndev > 1 and Gp > self.dev_max_groups:
            # the routing gate probed the device count nonblockingly and
            # may have allowed the pruned cap before the probe resolved
            # to a multi-device mesh; never mesh-dispatch a scan past the
            # BASE cap (the multi-minute stall the cap exists to prevent)
            import logging
            logging.getLogger(__name__).info(
                "padded group count %d exceeds the mesh kernel cap %d; "
                "serving from the host twin", Gp, self.dev_max_groups)
            if self.metrics is not None:
                self.metrics.inc(
                    "karpenter_solver_device_fallback_total",
                    labels={"reason": "group_cap"})
            return self._run_numpy(enc, ex_alloc, ex_used, ex_compat)
        n_bucket = self._bucket
        # checkpointed incremental solving rides the UNFUSED single-
        # device base kernel only (solver/incremental.py rationale);
        # requires the incremental encoder for the frontier/token edges
        from .incremental import CKPT_CHUNK, ckpt_eligible
        ck_on = (self.supports_ckpt_kernel and self._delta is not None
                 and not (ndev > 1 or use_pruned)
                 and ckpt_eligible(Gp, ndev=ndev, use_pruned=use_pruned,
                                   Fu=Fu))
        self._solve_mode = "full"
        sreason, sinfo = ("disabled" if not ck_on else None), None
        while True:
            if ndev > 1:
                out = self._dispatch_mesh(
                    arrays, T=T, D=Dp, Z=Z, C=C, G=Gp, E=Ep, P=Pp,
                    K=K, V=V, M=M, n_max=n_bucket, ndev=ndev,
                    dirty=mesh_dirty)
            elif use_pruned:
                # S resolved HERE, the call site both the local and the
                # RemoteSolver dispatch share — so the sidecar wire
                # carries the same selection width the local kernel uses
                o_buf = self._dispatch_pruned(
                    buf, T=T, D=Dp, Z=Z, C=C, G=Gp, E=Ep, P=Pp,
                    n_max=n_bucket, S=self.dev_pruned_slots)
                if int(o_buf[-1]):
                    # pruning insufficient for this input: host twin
                    # serves it, identically — never silently
                    import logging
                    logging.getLogger(__name__).info(
                        "pruned device kernel bailed (deep fill); "
                        "serving this solve from the host twin")
                    if self.metrics is not None:
                        self.metrics.inc(
                            "karpenter_solver_device_fallback_total",
                            labels={"reason": "pruned_bail"})
                    return self._run_numpy(enc, ex_alloc, ex_used,
                                           ex_compat)
                out = unpack_outputs1(o_buf[:-1], T, Dp, Z, C, Gp, Ep,
                                      Pp, n_bucket)
            else:
                out = None
                if ck_on and sreason is None:
                    out, sreason, sinfo = self._try_suffix(buf, stt,
                                                           n_bucket)
                if out is None:
                    if ck_on:
                        if self._solve_mode != "full":
                            # a suffix served but exhausted its slots:
                            # the grown retry is a bank-rebuilding full
                            self._solve_mode, sinfo = "full", None
                            sreason = "exhausted"
                        o_buf, bank = self._dispatch_ckpt(
                            buf, CK=CKPT_CHUNK,
                            **self._ckpt_statics(stt, n_bucket))
                        out = unpack_outputs1(o_buf, T, Dp, Z, C, Gp,
                                              Ep, Pp, n_bucket)
                        self._adopt_bank(buf, stt, n_bucket, bank, out,
                                         CKPT_CHUNK)
                    else:
                        o_buf = self._dispatch(buf, T=T, D=Dp, Z=Z,
                                               C=C, G=Gp, E=Ep, P=Pp,
                                               K=K, V=V, M=M,
                                               n_max=n_bucket, F=Fu,
                                               Q=Q)
                        out = unpack_outputs1(o_buf, T, Dp, Z, C, Gp,
                                              Ep, Pp, n_bucket)
            exhausted = (out["leftover"].sum() > 0
                         and int(out["num_nodes"][0]) >= n_bucket)
            if not exhausted or n_bucket >= self.n_max:
                break
            n_bucket = min(n_bucket * 4, self.n_max)
        self._bucket = self._settle_bucket(
            n_bucket, int(out["num_nodes"][0]))
        self._record_dispatch(
            kernel=("mesh" if ndev > 1 else
                    "pruned" if use_pruned else
                    "suffix" if self._solve_mode != "full" else
                    "ckpt" if ck_on else
                    "fused" if Fu > 1 else "base"),
            batch=1, Gp=Gp, Fu=Fu,
            fuse=arrays.get("fuse") if Fu > 1 else None)
        if ndev <= 1 and not use_pruned:
            if sinfo is not None:
                self.last_dispatch_stats.update(sinfo)
            self._solve_counter(sreason, sinfo)

        takes = out["takes"][:G]
        # slot axis: drop padded existing rows (E..Ep) — they are dead
        takes = np.concatenate([takes[:, :E], takes[:, Ep:]], axis=1)
        sm = _slotmap(E, Ep, Ep + n_bucket)
        final = dict(
            types=out["types"][sm], zones=out["zones"][sm],
            ct=out["ct"][sm], pool=out["pool"][sm],
            alive=out["alive"][sm], used=out["used"][sm][:, :D],
            E=E)
        return takes, out["leftover"][:G], final

    # ------------------------------------------------------------------
    def _decode(self, enc: SnapshotEncoding,
                existing: Sequence[ExistingNode],
                takes: np.ndarray, leftover: np.ndarray,
                final: dict, pods_by_group=None) -> SolveResult:
        E = final["E"]
        # per-priority-tier leftover report: host-side bookkeeping off
        # the solve's [G] leftover vector ({0: total} when the snapshot
        # carries no priorities) — the sim auditor and the preemption
        # planner read which tiers the solve starved
        from ..ops.hostpack import tier_leftovers
        self.last_tier_leftovers = tier_leftovers(
            np.asarray(leftover), enc.prio)
        # pods_by_group: the per-group pod LISTS this solve encoded —
        # the pipelined tick captures them at prepare time because a
        # rows-tier delta REPLACES g.pods for the next tick while this
        # tick's RPC is still in flight. None (every synchronous caller)
        # reads the live lists, which are the same objects then.
        gpods = pods_by_group if pods_by_group is not None \
            else [g.pods for g in enc.groups]
        assignments: Dict[str, str] = {}
        unschedulable: Dict[str, str] = {}
        #: slot -> list of pods (in canonical order)
        slot_pods: Dict[int, List] = {}
        slot_groups: Dict[int, List[int]] = {}

        if takes is None:
            # sparse placements (native fill): (g, slot, cnt) triples in
            # walk order — groups ascending, slots ascending and unique
            # within a group — so a linear walk with a per-group offset
            # reproduces the dense nonzero exactly, without ever
            # materializing the [G, N] matrix
            g_arr, s_arr, c_arr = final["placements"]
            groups = enc.groups
            cur_g, off = -1, 0
            # tolist() up front: iterating numpy scalars boxes one object
            # per element access — plain ints walk ~3x faster
            for gi, slot, cnt in zip(g_arr.tolist(), s_arr.tolist(),
                                     c_arr.tolist()):
                if gi != cur_g:
                    cur_g, off = gi, 0
                chunk = gpods[gi][off:off + cnt]
                off += cnt
                if slot < E:
                    nm = existing[slot].name
                    for p in chunk:
                        assignments[p.full_name()] = nm
                else:
                    sp = slot_pods.get(slot)
                    if sp is None:
                        slot_pods[slot] = list(chunk)
                        slot_groups[slot] = [gi]
                    else:
                        sp.extend(chunk)
                        slot_groups[slot].append(gi)
            for gi in np.nonzero(leftover)[0]:
                gp = gpods[int(gi)]
                for p in gp[len(gp) - int(leftover[gi]):]:
                    unschedulable[p.full_name()] = \
                        "no capacity in any nodepool"
            return self._decode_nodes(enc, assignments, unschedulable,
                                      slot_pods, slot_groups, final)

        run_log = final.get("run_log") or {}
        # one global nonzero instead of one per group: np.nonzero walks
        # row-major, so each group's slots arrive contiguous and ordered
        gnz, snz = np.nonzero(takes)
        bounds = np.searchsorted(gnz, np.arange(len(enc.groups) + 1))
        for g in enc.groups:
            off = 0
            gp = gpods[g.index]
            # topology pours stripe pods across slots; replay their
            # placement order. Plain fills are slot-order chunks.
            placement = run_log.get(g.index)
            if placement is None:
                lo, hi = bounds[g.index], bounds[g.index + 1]
                placement = [(int(s), int(takes[g.index, s]))
                             for s in snz[lo:hi]]
            def place(slot, chunk):
                if slot < E:
                    for p in chunk:
                        assignments[p.full_name()] = existing[slot].name
                else:
                    slot_pods.setdefault(int(slot), []).extend(chunk)
                    if g.index not in slot_groups.setdefault(int(slot), []):
                        slot_groups[int(slot)].append(g.index)

            for entry in placement:
                if entry[0] == "cyc":
                    # a committed periodic jump: `pattern` repeated k times.
                    # Pods stripe round-robin over the pattern; entry j of
                    # the pattern owns a strided slice of the pod list.
                    _, pattern, k = entry
                    d_n = sum(ln for _, ln in pattern)
                    pos = 0
                    for slot, ln in pattern:
                        if ln == 1:
                            chunk = gp[off + pos:off + d_n * k:d_n]
                        else:
                            chunk = []
                            for p_i in range(k):
                                base = off + pos + p_i * d_n
                                chunk.extend(gp[base:base + ln])
                        place(slot, chunk)
                        pos += ln
                    off += d_n * k
                    continue
                slot, cnt = entry
                place(slot, gp[off:off + cnt])
                off += cnt
            for p in gp[off:]:  # leftovers — could not be scheduled
                unschedulable[p.full_name()] = "no capacity in any nodepool"
        return self._decode_nodes(enc, assignments, unschedulable,
                                  slot_pods, slot_groups, final)

    def _decode_nodes(self, enc: SnapshotEncoding, assignments,
                      unschedulable, slot_pods, slot_groups,
                      final: dict) -> SolveResult:
        """Mint NewNodeClaims from the per-slot pod lists — the decode
        tail shared by the dense-takes and sparse-placement paths."""
        new_nodes: List[NewNodeClaim] = []
        #: (zone-mask, ct-mask) -> per-type best price; nodes share few
        #: distinct mask patterns (usually one per zone), so the [T, Z, C]
        #: reduction runs once per pattern instead of once per node
        best_cache: Dict[bytes, np.ndarray] = {}
        #: (type-mask, zone-mask, ct-mask) -> cheapest-first type-name
        #: list; same sharing argument as best_cache (one argsort per
        #: pattern instead of one per node)
        order_cache: Dict[bytes, List[str]] = {}
        #: (pool, groups, fixed-zone) -> merged node requirements; ladders
        #: mint hundreds of nodes with identical group mixes
        reqs_cache: Dict[Tuple, Requirements] = {}
        zfix = final.get("zfix")
        for slot in sorted(slot_pods):
            pods = slot_pods[slot]
            pool = enc.pools[int(final["pool"][slot])]
            tmask = final["types"][slot]
            zmask = final["zones"][slot]
            cmask = final["ct"][slot]
            # price per candidate type under the node's (zone, ct) masks
            ck = zmask.tobytes() + cmask.tobytes()
            ok = tmask.tobytes() + ck
            type_names = order_cache.get(ok)
            if type_names is None:
                best = best_cache.get(ck)
                if best is None:
                    pz = np.where(
                        enc.avail & zmask[None, :, None]
                        & cmask[None, None, :],
                        enc.price, np.int64(1) << 62)
                    best = best_cache[ck] = pz.min(axis=(1, 2))
                # (price, name) order: types are name-sorted in the
                # encoding, so a stable argsort on price alone breaks
                # ties by name
                idx = np.nonzero(tmask)[0]
                order = idx[np.argsort(best[idx], kind="stable")]
                type_names = order_cache[ok] = \
                    [enc.type_names[i] for i in order]
            zf = int(zfix[slot]) if zfix is not None else -1
            # key on the groups that CONTRIBUTE requirements: empty-req
            # groups can't change the union, and dropping them collapses
            # most per-node keys onto a handful of shared cache entries
            # (at the G-axis envelope a node hosts ~100 groups of which
            # only the selector-bearing few have requirements)
            gs = tuple(gi for gi in slot_groups[slot]
                       if enc.groups[gi].reqs)
            rk = (int(final["pool"][slot]), gs, zf)
            reqs = reqs_cache.get(rk)
            if reqs is None:
                reqs = pool.spec.nodepool.scheduling_requirements()
                for gi in gs:
                    reqs = reqs.union(enc.groups[gi].reqs)
                if zf >= 0:
                    # topology pinned this node's zone (_choose_zone); the
                    # oracle narrows node requirements with ZONE IN [chosen]
                    reqs = reqs.add(Requirement.new(
                        L.ZONE, IN, [enc.zones[zf]]))
                reqs_cache[rk] = reqs
            used_vec = final["used"][slot]
            # per-group chunks arrive in ascending (ns, name) order, so
            # the concatenation is a few sorted runs — timsort is ~O(n);
            # _full_name is set eagerly in Pod.__init__ (attribute access
            # beats a method call at 50k pods per solve)
            names = [p._full_name for p in pods]
            names.sort()
            new_nodes.append(NewNodeClaim(
                nodepool=pool.spec.nodepool.metadata.name,
                requirements=reqs,
                pod_names=names,
                instance_type_names=type_names,
                requests=Resources({d: int(used_vec[i])
                                    for i, d in enumerate(enc.dims)}),
                taints=list(pool.spec.nodepool.template.taints),
            ))
        return SolveResult(new_nodes=new_nodes,
                           existing_assignments=assignments,
                           unschedulable=unschedulable)
