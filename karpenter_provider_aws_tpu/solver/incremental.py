"""Incremental-solve policy: when a checkpointed scan may serve, and
which static suffix bucket a dirty frontier resolves to.

The FFD ``lax.scan`` carry entering group *i* is a pure function of
groups ``< i`` in the restriction-stable canonical order
(models/encoding.py), so a tick whose dirty rows all sit at or past a
frontier index can restore the deepest checkpoint at or below the
frontier and re-scan only the suffix — byte-identical to the
from-scratch solve by construction (the suffix scans the SAME padded
arena rows through the SAME step function from the SAME carry the full
solve would have reached). This module centralizes the three decisions
every dispatch site (solver/tpu.py, sidecar/server.py, and the numpy
host twin) must make identically:

- ``ckpt_eligible``: which shape classes record checkpoints at all.
  The checkpointed kernel is the UNFUSED single-device scan — the
  fused/pruned/mesh kernels keep their own scan shapes, and their
  envelopes (huge G, multi-device) are exactly where a per-chunk
  checkpoint bank would be carry-width-expensive anyway.
- ``suffix_plan``: frontier -> (resume chunk, static suffix length).
  Suffix lengths round UP a static bucket ladder (the tenancy
  T-ladder: pow2 with a 1.5x midpoint, ``tenancy/bucketing.py``) so a
  warm frontier wobbling a few groups never triggers a recompile —
  at most ``O(log G)`` suffix shape classes exist per arena shape.
  Rounding up only ever resumes EARLIER (deeper prefix re-scanned),
  which is always exact.
- ``suffix_buckets``: every suffix length a shape class can produce —
  the prime set hack/aotprime.py records and solver warmup compiles.

Bank *validity* is intentionally not decided here: it is a token
equality (delta epoch + the encoder version the bank's arena
reflected) owned by the dispatch sites, because the client solver and
the sidecar server track versions on different wires (DeltaEncoder
state token vs patch-frame base_version).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from ..tenancy.bucketing import _pow15

#: checkpoint stride: one carry snapshot every CKPT_CHUNK groups. The
#: bank costs (G / CKPT_CHUNK) carry copies of device memory per solve
#: — still small next to the takes table — and the wasted prefix
#: re-scan below the frontier is at most CKPT_CHUNK - 1 groups. Per-
#: group scan cost is dispatch-bound on CPU (~0.14ms/group at the 50k
#: shape), so a stride of 2 buys the warm tick one-to-two fewer groups
#: than 4 did and that is the difference between meeting the 1.5ms
#: suffix budget and missing it.
CKPT_CHUNK = 2

#: largest padded group count that records checkpoints. Past this the
#: bank's [G/CK, N, ...] carry stack stops being small next to the
#: arena, and the big-G envelopes belong to the pruned kernel anyway
#: (which is ckpt-ineligible by shape).
CKPT_MAX_GROUPS = 512


def ckpt_eligible(Gp: int, *, ndev: int = 1, use_pruned: bool = False,
                  Fu: int = 1, CK: int = CKPT_CHUNK) -> bool:
    """May this dispatch record/consume a checkpoint bank? Purely a
    shape/engine gate — bank freshness is the caller's token check."""
    return (ndev <= 1 and not use_pruned and Fu <= 1
            and Gp >= 2 * CK and Gp <= CKPT_MAX_GROUPS
            and Gp % CK == 0)


def suffix_plan(frontier: int, Gp: int, CK: int = CKPT_CHUNK,
                GL: int = None) -> Tuple[int, int]:
    """``(resume_chunk, SUF)`` for a dirty frontier against a Gp-group
    arena whose live bound is GL (chunk-aligned end of the non-empty
    groups, ``live_bound``; None means Gp): the suffix scans chunks
    ``[resume_chunk, GL/CK)`` — i.e. groups ``[resume_chunk*CK, GL)``
    — from the bank's entry carry at ``resume_chunk``. Groups past GL
    are empty, hence carry no-ops the scan skips for free. SUF is the
    bucketed chunk count (static: one compiled suffix kernel per
    value). Invariants: ``resume_chunk * CK <= frontier`` (never skips
    a dirty row — dirty rows are non-empty, so frontier < GL) and
    ``SUF >= 1`` (even a clean tick re-scans one chunk — cheaper than
    special-casing an empty suffix into a separate code path)."""
    GLC = (GL if GL is not None else Gp) // CK
    j = min(max(frontier, 0) // CK, GLC - 1)
    SUF = min(_pow15(GLC - j), GLC)
    return GLC - SUF, SUF


def suffix_buckets(Gp: int, CK: int = CKPT_CHUNK,
                   GL: int = None) -> Tuple[int, ...]:
    """Every SUF value ``suffix_plan`` can emit for this arena shape,
    ascending — the compile/prime set (aotprime + solver warmup)."""
    GLC = (GL if GL is not None else Gp) // CK
    return tuple(sorted({min(_pow15(GLC - j), GLC) for j in range(GLC)}))


def live_bound(buf, *, T: int, D: int, G: int,
               CK: int = CKPT_CHUNK) -> int:
    """Chunk-aligned bound of the non-empty groups of a packed arena:
    the smallest multiple of CK covering every group with n > 0 (the
    ``n`` vector sits at word ``T*D + G*D`` of the i64 section —
    ops/hostpack.py in_layout_i64). Groups at or past the bound are
    padding (or emptied rows), and an empty group is a carry no-op —
    the FFD step places min(n, ...) = 0 pods and opens ceil(0/cap) = 0
    nodes — so a suffix scan may stop there with byte-identical
    outputs. Returns 0 for an all-empty arena (no dirty group can
    exist, so no suffix is ever planned against it)."""
    off = T * D + G * D
    n = np.asarray(buf[off:off + G])
    nz = np.nonzero(n)[0]
    if not nz.size:
        return 0
    return -(-(int(nz[-1]) + 1) // CK) * CK
