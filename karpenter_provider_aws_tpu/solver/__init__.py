from .cpu import CPUSolver, pod_group_signature, pod_sort_key
from .types import (DaemonOverhead, ExistingNode, NewNodeClaim, NodePoolSpec,
                    SchedulingSnapshot, SolveResult, Solver)

__all__ = ["Solver", "CPUSolver", "SchedulingSnapshot", "SolveResult",
           "NewNodeClaim", "NodePoolSpec", "ExistingNode", "DaemonOverhead",
           "pod_sort_key", "pod_group_signature"]
