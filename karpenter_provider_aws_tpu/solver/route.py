"""Host-vs-device cost routing for the solve kernels.

The host numpy twin and the device kernel compute the SAME math and
produce decision-identical outputs (enforced by the equivalence suites),
so engine choice is purely a latency decision. Which engine wins is a
hardware fact, not a code fact: a solve's device cost is dominated by the
link (PCIe ≈ microseconds, a tunneled remote TPU ≈ tens of ms floor, a
gRPC sidecar hop ≈ network RTT) plus payload/bandwidth, while the host
cost scales with the constraint-tensor volume. Hardcoding either side
loses badly somewhere — so measure, don't guess:

- per shape bucket (the same padded statics that key the XLA compile
  cache), keep an EWMA of observed host and device latency;
- first encounter runs BOTH (the device run doubles as the jit warm-up;
  its compile is excluded by timing a second dispatch);
- steady state runs the cheaper side and re-probes the other side in a
  background thread every ``REFRESH_EVERY`` solves, so the router adapts
  when the link or the shapes drift without ever blocking a solve.

This mirrors how XLA itself places ops host-vs-device by cost model, and
keeps the <200ms p99 target independent of where the TPU happens to live.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple

ALPHA = 0.3          # EWMA weight of the newest observation
#: re-probe the losing engine every N solves per bucket. The probe runs in
#: a background thread concurrently with subsequent solves, so it must be
#: rare enough not to show in p99 (<0.5% of solves even counting the 2-3
#: rounds a slow device probe overlaps); at a 1s provisioning cadence 512
#: still re-checks the link every ~8 minutes
REFRESH_EVERY = 512


#: sentinel distinguishing park_dev() (park everything — the legacy
#: whole-engine verdict) from park_dev(endpoint=None) (park the
#: anonymous single-endpoint evidence only)
_ALL_ENDPOINTS = object()


class Router:
    def __init__(self, metrics=None, name: str = "solver"):
        self._mu = threading.Lock()
        self._stats: Dict[Tuple, Dict] = {}
        #: per-(endpoint, bucket) dev evidence. The host twin is local
        #: and shared, but "the dev engine" is a specific peer once a
        #: fleet is in play: one slow or parked replica must never
        #: poison the verdict the other replicas earned.
        self._dev: Dict[Tuple, float] = {}
        #: current endpoint context (fleet sets this on rebind). None =
        #: the legacy single-endpoint mode: dev evidence lives in
        #: _stats[bucket]["dev"] exactly as before.
        self.endpoint: Optional[str] = None
        self.metrics = metrics
        self.name = name
        #: dev-engine liveness cache; None = the shared local-device
        #: probe. RemoteSolver swaps in a sidecar ping (its dev engine is
        #: the gRPC peer, not local jax).
        self.alive: Optional["AliveCache"] = None

    @staticmethod
    def _blend(prev: Optional[float], ms: float) -> float:
        # parking (ms >= DEV_FAILED_MS) and UN-parking (first healthy
        # observation after a park) are ABSOLUTE, not EWMA-blended: a
        # blend of 1e12 with anything real stays effectively-parked
        # for ~90 observations, so a recovered dev engine would never
        # win routing back within a refresh cycle
        if prev is None or ms >= DEV_FAILED_MS or prev >= DEV_FAILED_MS:
            return ms
        return (1.0 - ALPHA) * prev + ALPHA * ms

    def observe(self, bucket: Tuple, side: str, ms: float) -> None:
        with self._mu:
            st = self._stats.setdefault(
                bucket, {"host": None, "dev": None, "n": 0})
            if side == "dev" and self.endpoint is not None:
                key = (self.endpoint, bucket)
                self._dev[key] = self._blend(self._dev.get(key), ms)
                return
            st[side] = self._blend(st[side], ms)

    def _dev_of(self, bucket: Tuple, st: Dict) -> Optional[float]:
        """Effective dev estimate for the CURRENT endpoint (lock held).

        Own evidence wins; a replica with no history for this bucket
        falls back to the aggregate of the other replicas' non-parked
        estimates (a fresh scale-out replica inherits the fleet's
        measured cost instead of re-calibrating every shape), and only
        then to the legacy anonymous store."""
        if self.endpoint is not None:
            own = self._dev.get((self.endpoint, bucket))
            if own is not None:
                return own
            peers = [v for (ep, b), v in self._dev.items()
                     if b == bucket and v < DEV_FAILED_MS]
            if peers:
                return sum(peers) / len(peers)
        return st["dev"]

    def park_dev(self, ms: float = None, endpoint=_ALL_ENDPOINTS) -> None:
        """Park dev EWMAs (circuit breaker opened); the next successful
        background probe un-parks per bucket via observe().

        No ``endpoint`` argument parks EVERY bucket of EVERY endpoint —
        the dev engine is down as a whole. With ``endpoint=`` only that
        replica's evidence is parked: the rest of the fleet keeps its
        earned verdicts."""
        if ms is None:
            ms = DEV_FAILED_MS
        with self._mu:
            if endpoint is _ALL_ENDPOINTS:
                for st in self._stats.values():
                    st["dev"] = ms
                for key in self._dev:
                    self._dev[key] = ms
                return
            for bucket in self._stats:
                self._dev[(endpoint, bucket)] = ms
            for key in list(self._dev):
                if key[0] == endpoint:
                    self._dev[key] = ms

    def forget_endpoint(self, endpoint: str) -> None:
        """Drop a removed replica's evidence so the aggregate fallback
        never averages in a peer that left the membership."""
        with self._mu:
            for key in [k for k in self._dev if k[0] == endpoint]:
                del self._dev[key]

    def choose(self, bucket: Tuple):
        """"both" on first encounter, else ("host"|"dev", refresh_other)."""
        with self._mu:
            st = self._stats.setdefault(
                bucket, {"host": None, "dev": None, "n": 0})
            st["n"] += 1
            dev = self._dev_of(bucket, st)
            if st["host"] is None or dev is None:
                return "both"
            side = "host" if st["host"] <= dev else "dev"
            return side, (st["n"] % REFRESH_EVERY == 0)

    def snapshot(self) -> Dict[Tuple, Dict]:
        """Per-bucket stats with ``dev`` resolved for the CURRENT
        endpoint context (same shape as always: {bucket: {host,dev,n}})."""
        with self._mu:
            out = {}
            for k, v in self._stats.items():
                d = dict(v)
                d["dev"] = self._dev_of(k, v)
                out[k] = d
            return out


#: EWMA assigned to a device side that raised: effectively routes every
#: subsequent solve to the host twin until a background probe succeeds
DEV_FAILED_MS = 1e12


class AliveCache:
    """Nonblocking liveness verdict around a potentially slow/blocking
    probe: True is permanent, False expires (recheck), unknown kicks ONE
    background probe and reports None. The device and the gRPC sidecar
    each get an instance — their notion of 'is the dev engine reachable'
    differs, but the caching discipline is the same."""

    def __init__(self, probe: Callable[[], bool],
                 recheck_s: float = 300.0):
        self._probe = probe
        self._recheck_s = recheck_s
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._probing = False
        self._verdict: Optional[bool] = None
        self._at = 0.0
        self._in_flight = threading.Event()

    def blocking(self) -> bool:
        """At most ONE probe runs at a time: concurrent callers wait on
        the in-flight probe's verdict instead of each launching their own
        (the probe can be a 90s subprocess — a thundering herd of them
        serializes behind the GIL and multiplies the stall)."""
        with self._mu:
            while True:
                if self._verdict is True:
                    return True
                if self._verdict is False and \
                        time.monotonic() - self._at < self._recheck_s:
                    return False
                if not self._probing:
                    self._probing = True
                    break
                self._cv.wait()  # ride the in-flight probe's verdict
        try:
            verdict = bool(self._probe())
        except Exception:
            verdict = False
        with self._mu:
            self._verdict = verdict
            self._at = time.monotonic()
            self._probing = False
            self._cv.notify_all()
            return verdict

    def mark_failed(self) -> None:
        """External evidence the engine is down (circuit breaker opened):
        cache a False verdict now — expiring like any probed False, so
        recovery is still noticed after recheck_s."""
        with self._mu:
            self._verdict = False
            self._at = time.monotonic()

    def mark_ok(self) -> None:
        """External evidence the engine is healthy (half-open probe
        succeeded): True is permanent, exactly like a probed True."""
        with self._mu:
            self._verdict = True
            self._at = time.monotonic()

    def nonblocking(self) -> Optional[bool]:
        with self._mu:
            if self._verdict is True:
                return True
            if self._verdict is False and \
                    time.monotonic() - self._at < self._recheck_s:
                return False
        if not self._in_flight.is_set():
            self._in_flight.set()

            def _bg():
                try:
                    self.blocking()
                finally:
                    self._in_flight.clear()

            threading.Thread(target=_bg, daemon=True,
                             name="alive-probe").start()
        return None


def _probe_device(timeout: float = 90.0) -> bool:
    """Probe jax backend liveness in a SUBPROCESS with a hard timeout.

    A wedged accelerator link (observed with a tunneled remote TPU after a
    crashed client) makes jax backend init block forever rather than
    raise; an in-process try/except cannot defend against that — hence
    the subprocess. Wrapped by ``_device_alive`` (an AliveCache) so the
    solve path only ever sees the nonblocking verdict."""
    import subprocess
    import sys
    # inherit an explicit platform override (tests force cpu via
    # jax.config.update — which, unlike the JAX_PLATFORMS env var,
    # reliably skips a wedged accelerator plugin)
    plat = None
    if "jax" in sys.modules:
        try:
            plat = sys.modules["jax"].config.jax_platforms
        except Exception:
            plat = None
    # the probe child arms a SIGALRM self-destruct BEFORE importing jax:
    # a probe against a wedged plugin busy-spins, and if the parent exits
    # mid-probe (bench printing its JSON and quitting with the daemon
    # probe thread in flight) subprocess.run's timeout-kill never runs —
    # the orphan would spin forever and eat the host CPU the benches
    # measure (observed: four orphans accumulated across bench runs on a
    # single-core host). The kernel delivers SIGALRM regardless of what
    # the plugin is doing; default disposition terminates the process.
    code = (f"import signal; signal.alarm({int(timeout) + 5})\n"
            "import jax\n")
    if plat:
        code += f"jax.config.update('jax_platforms', {plat!r})\n"
    code += ("ds = jax.devices()\n"
             "print('ok', len(ds), ds[0].platform)")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              timeout=timeout, capture_output=True)
        if proc.returncode != 0 or b"ok" not in proc.stdout:
            return False
        global _DEV_COUNT, _DEV_PLATFORM
        try:
            # parse relative to the 'ok' token: runtime banners may
            # precede it on stdout, and a misparse here would silently
            # disable the mesh path on a healthy multi-chip host
            parts = proc.stdout.split()
            i = parts.index(b"ok")
            _DEV_COUNT = int(parts[i + 1])
            _DEV_PLATFORM = parts[i + 2].decode()
        except (IndexError, ValueError):
            _DEV_COUNT = 1
        return True
    except Exception:
        return False


#: device count/platform observed by the last successful liveness probe
#: (the mesh dispatch decision rides the same subprocess probe as
#: liveness — a wedged link must never block a count query either)
_DEV_COUNT = 0
_DEV_PLATFORM = ""


def dev_device_count() -> int:
    """Nonblocking: devices on the probed backend; 0 while the probe is
    pending or the backend is dead. Single-device hosts dispatch the
    packed kernel; multi-device hosts dispatch the type-parallel mesh
    solve (parallel/mesh.py)."""
    return _DEV_COUNT if _device_alive.nonblocking() is True else 0


def dev_platform() -> str:
    """Nonblocking: probed backend platform name ('tpu', 'cpu', ...);
    'unavailable' while dead/pending — benches record which engine
    ACTUALLY served (a wedged tunnel must never be reported as tpu)."""
    alive = _device_alive.nonblocking()
    return _DEV_PLATFORM if alive is True else "unavailable"


#: the shared local-device liveness cache (Router.alive default)
_device_alive = AliveCache(_probe_device)


def device_alive() -> bool:
    """Cached backend-liveness verdict; the probe's 90s subprocess
    deadline lives in ``_probe_device``."""
    return _device_alive.blocking()


def device_alive_nonblocking() -> Optional[bool]:
    return _device_alive.nonblocking()


def dev_engine_usable(router: Router) -> bool:
    """Nonblocking liveness verdict for a router's dev engine — its own
    alive cache when set (RemoteSolver pings its sidecar), else the
    shared local-device probe. A pending probe (None) counts as not
    usable: callers fall back to the bit-identical host twin for this
    solve and the background probe resolves for later ones — an explicit
    device request must never HANG on a wedged link (first array
    creation blocks forever, no error)."""
    cache = router.alive if router.alive is not None else _device_alive
    return cache.nonblocking() is True


def routed(router: Router, bucket: Tuple,
           host_fn: Callable[[], object],
           dev_fn: Callable[[], object]):
    """Run the cheaper engine for this bucket; keep both EWMAs warm.

    The host twin is decision-identical, so a device failure (sidecar
    down, jax backend unavailable, link wedged) must never fail the solve:
    every device invocation degrades to the host twin and parks the
    device EWMA at DEV_FAILED_MS so routing stays on host until a
    background probe observes the device healthy again."""
    choice = router.choose(bucket)
    metrics = router.metrics
    if choice == "both":
        alive = (router.alive or _device_alive).nonblocking()
        if alive is None:
            # verdict pending (background probe running): serve the host
            # twin WITHOUT recording a dev observation, so this bucket
            # re-enters calibration once the probe lands
            t0 = time.perf_counter()
            out = host_fn()
            router.observe(bucket, "host",
                           (time.perf_counter() - t0) * 1000)
            if metrics is not None:
                metrics.inc(f"karpenter_{router.name}_route_total",
                            labels={"route": "probe-pending"})
            return out
        if alive is False:
            # wedged/absent device: park it and serve from the host twin
            router.observe(bucket, "dev", DEV_FAILED_MS)
            choice = ("host", False)
            if metrics is not None:
                metrics.inc(f"karpenter_{router.name}_route_total",
                            labels={"route": "dev-unreachable"})
    if choice == "both":
        try:
            dev_fn()  # first device run pays the XLA compile; not recorded
            t0 = time.perf_counter()
            dev_fn()
            router.observe(bucket, "dev", (time.perf_counter() - t0) * 1000)
        except Exception:
            router.observe(bucket, "dev", DEV_FAILED_MS)
        t0 = time.perf_counter()
        out = host_fn()  # identical decisions; return either
        router.observe(bucket, "host", (time.perf_counter() - t0) * 1000)
        if metrics is not None:
            metrics.inc(f"karpenter_{router.name}_route_total",
                        labels={"route": "calibrate"})
        return out
    side, refresh = choice
    if side == "dev":
        try:
            t0 = time.perf_counter()
            out = dev_fn()
            router.observe(bucket, "dev", (time.perf_counter() - t0) * 1000)
        except Exception:
            router.observe(bucket, "dev", DEV_FAILED_MS)
            side = "host"
            if metrics is not None:
                metrics.inc(f"karpenter_{router.name}_route_total",
                            labels={"route": "dev-failed"})
    if side == "host":
        t0 = time.perf_counter()
        out = host_fn()
        router.observe(bucket, "host", (time.perf_counter() - t0) * 1000)
    if metrics is not None:
        metrics.inc(f"karpenter_{router.name}_route_total",
                    labels={"route": side})
    if refresh:
        other_side = "dev" if side == "host" else "host"
        other_fn = dev_fn if side == "host" else host_fn

        def _probe():
            try:
                # a dev_fn against a wedged link blocks forever; gate the
                # probe on the subprocess liveness check (in THIS thread —
                # its up-to-90s wait must never block a solve). The False
                # verdict expires, so recovery is still noticed.
                # blocking is correct HERE (the probe daemon thread):
                # waiting lets a just-recovered dev engine be re-measured
                # this cycle instead of one REFRESH_EVERY later
                if other_side == "dev" \
                        and not (router.alive or _device_alive).blocking():
                    return
                t0 = time.perf_counter()
                other_fn()
                router.observe(bucket, other_side,
                               (time.perf_counter() - t0) * 1000)
            except Exception:  # pragma: no cover - probe must never raise
                pass

        threading.Thread(target=_probe, daemon=True,
                         name=f"{router.name}-route-probe").start()
    return out
