"""Preference relaxation: soft scheduling constraints honored when
possible, dropped when they block a pod.

Upstream core treats preferred scheduling terms as REQUIRED and, when a
pod cannot schedule, relaxes one preference and retries (the scheduler's
preference-relaxation loop; consumed by this provider per SURVEY §3.2).
This module is that loop for the batch solvers:

- a pod's *preference chain* is its preferred (anti-)affinity terms in
  declaration order, then its ScheduleAnyway topology-spread constraints
  in declaration order;
- at relax level L the first L preferences are REMOVED and the rest are
  HARDENED (required=True / DoNotSchedule);
- the wrapper solves with every preference-bearing pod hardened at its
  current level, bumps the level of exactly the pods that came back
  unschedulable and still have something to relax, and re-solves; the
  loop ends when nothing bumps (bounded by the total relaxation budget).

The wrapper works at GROUP granularity: it computes the canonical pod
grouping once (the same grouping the encoder needs — handed down so the
50k-pod walk happens exactly once per solve), reads the preference chain
off each group representative (the chain is a function of the scheduling
signature, which all members share), and in relax rounds rebuilds only
the partitions of soft groups whose levels moved — pods with no
preferences are never walked again. Hardened clones are cached on the
pod object, so steady-state re-solves reuse them. Both solver engines
share this wrapper, which keeps CPU/TPU decision equality by
construction.
"""

from __future__ import annotations

import copy
from typing import Callable, Dict, List, Tuple

from ..apis.objects import Pod, PodAffinityTerm, TopologySpreadConstraint
from ..models.encoding import canonical_group_order, canonical_pod_groups
from .types import SchedulingSnapshot, SolveResult

#: per-pod memo key for preference_count; the apis layer owns it so the
#: invalidator (invalidate_scheduling_caches) and both lookup sites here
#: can never silently disagree
from ..apis.objects import PREF_COUNT_MEMO  # noqa: E402


def preference_count(pod: Pod) -> int:
    """Length of the pod's preference chain (0 = nothing to relax).
    A function of the pod's scheduling signature, so one call per GROUP
    representative covers every member
    (invalidate_scheduling_caches clears the memo)."""
    n = pod.__dict__.get(PREF_COUNT_MEMO)
    if n is None:
        n = sum(1 for a in pod.pod_affinity if not a.required) \
            + sum(1 for c in pod.topology_spread
                  if c.when_unsatisfiable != "DoNotSchedule")
        pod.__dict__[PREF_COUNT_MEMO] = n
    return n


def harden(pod: Pod, level: int) -> Pod:
    """A clone of `pod` with the first `level` preferences removed and the
    remaining ones promoted to required. level=0 hardens everything."""
    cache = pod.__dict__.setdefault("_hardened", {})
    hit = cache.get(level)
    if hit is not None:
        return hit
    clone = copy.copy(pod)  # shallow: shares metadata (same identity)
    # caches that depend on the (changed) topology fields must not leak:
    # a stale signature would group a hardened clone with the raw pod and
    # make relaxation a no-op (the attribute list lives with Pod)
    from ..apis.objects import invalidate_scheduling_caches
    invalidate_scheduling_caches(clone)
    dropped = 0
    aff: List[PodAffinityTerm] = []
    for a in pod.pod_affinity:
        if a.required:
            aff.append(a)
        elif dropped < level:
            dropped += 1  # relaxed away
        else:
            aff.append(PodAffinityTerm(topology_key=a.topology_key,
                                       group=a.group, anti=a.anti,
                                       required=True))
    spread: List[TopologySpreadConstraint] = []
    for c in pod.topology_spread:
        if c.when_unsatisfiable == "DoNotSchedule":
            spread.append(c)
        elif dropped < level:
            dropped += 1
        else:
            spread.append(TopologySpreadConstraint(
                max_skew=c.max_skew, topology_key=c.topology_key,
                when_unsatisfiable="DoNotSchedule", group=c.group))
    clone.pod_affinity = aff
    clone.topology_spread = spread
    cache[level] = clone
    return clone


def _group_signature_of(pod: Pod) -> Tuple:
    from ..models.encoding import pod_group_signature
    return pod_group_signature(pod)


def solve_with_preferences(
        solve_core: Callable[..., SolveResult],
        snapshot: SchedulingSnapshot, metrics=None) -> SolveResult:
    raw_groups = canonical_pod_groups(snapshot.pods)
    #: group position -> chain length (>0 only for soft groups)
    chains: Dict[int, int] = {}
    for gi, (_sig, plist) in enumerate(raw_groups):
        n = preference_count(plist[0])
        if n:
            chains[gi] = n
    if not chains:
        return solve_core(snapshot, pod_groups=raw_groups)
    #: per-pod relaxation level (pods of one group can diverge: only the
    #: members that came back unschedulable bump)
    level: Dict[int, int] = {id(p): 0 for gi in chains
                             for p in raw_groups[gi][1]}
    # relaxing one pod can newly block another (e.g. a relaxed pod lands
    # on a node and its group-membership counter now repels a hardened
    # anti-affinity pod), so the loop bound is the TOTAL relaxation
    # budget, not the longest single chain — every round that doesn't
    # terminate bumps at least one pod's level
    max_rounds = 1 + sum(chains[gi] * len(raw_groups[gi][1])
                         for gi in chains)
    result: SolveResult = None  # type: ignore[assignment]
    rounds = 0

    def partitions_of(gi: int) -> List[Tuple[Tuple, List[Pod]]]:
        """Split one soft group into per-level partitions of hardened
        clones (partition preserves the (ns, name) member order)."""
        parts: Dict[int, List[Pod]] = {}
        for p in raw_groups[gi][1]:
            parts.setdefault(level[id(p)], []).append(p)
        return [(_group_signature_of(h0 := harden(members[0], lv)),
                 [h0] + [harden(p, lv) for p in members[1:]])
                for lv, members in parts.items()]

    #: gi -> current partitions; recomputed only when the group's levels
    #: moved (the bump loop below): steady-state rounds walk only the
    #: pods of groups that actually changed, not all 50k
    soft_parts: Dict[int, List[Tuple[Tuple, List[Pod]]]] = {
        gi: partitions_of(gi) for gi in chains}
    for _ in range(max_rounds):
        # group-level assembly: hard groups pass through untouched; soft
        # groups contribute their current hardened partitions
        assembled: List[Tuple[Tuple, List[Pod]]] = []
        for gi, (sig, plist) in enumerate(raw_groups):
            if gi in chains:
                assembled.extend(soft_parts[gi])
            else:
                assembled.append((sig, plist))
        groups = canonical_group_order(assembled)
        from itertools import chain as _chain
        pods = list(_chain.from_iterable(pl for _, pl in groups))
        result = solve_core(SchedulingSnapshot(
            pods=pods, nodepools=snapshot.nodepools,
            existing_nodes=snapshot.existing_nodes,
            daemon_overheads=snapshot.daemon_overheads,
            zones=snapshot.zones), pod_groups=groups)
        bumped = False
        if result.unschedulable:
            for gi in chains:
                cap = chains[gi]
                moved = False
                for p in raw_groups[gi][1]:
                    if level[id(p)] < cap and \
                            p.full_name() in result.unschedulable:
                        level[id(p)] += 1
                        moved = True
                if moved:
                    soft_parts[gi] = partitions_of(gi)
                    bumped = True
        if not bumped:
            break
        rounds += 1
    if rounds:
        # each extra round is a FULL re-solve — a latency cliff that must
        # never be silent (same stance as the oracle-fallback counter)
        import logging
        logging.getLogger(__name__).info(
            "preference relaxation took %d extra solve round(s) across %d "
            "soft group(s)", rounds, len(chains))
        if metrics is not None:
            metrics.inc("karpenter_solver_preference_relaxation_rounds_total",
                        value=float(rounds))
    return result
