"""The CPU solver: reference-equivalent FFD bin-packing, the correctness
oracle the TPU solver must match decision-for-decision.

Algorithm (designs/bin-packing.md:17-42 + core scheduler behavior):

1. Sort pending pods by descending (cpu, memory) request, name ascending —
   a deterministic total order shared with the TPU solver.
2. For each pod, first-fit in a fixed order: existing cluster nodes (name
   order), then open in-flight nodes (creation order), else open a new node
   from the first admitting NodePool (weight-descending, name ascending).
3. An open node carries a *set* of candidate instance types that narrows as
   pods land (aggregate requests must fit at least one candidate's
   allocatable; pod requirements intersect away incompatible types). The
   launcher later picks the cheapest viable types (Truncate(60),
   instance.go:106).
4. Topology spread / pod (anti-)affinity are enforced per placement with
   domain counters; an open node's undecided zone narrows to the chosen
   domain (min-count, lexicographic tie-break — deterministic).
5. NodePool limits gate adding pods (pool usage includes planned nodes).

Performance machinery (none of it changes any decision):

- Resource fit is vectorized: each open node keeps an int64 allocatable
  matrix over the solve's resource-dimension universe; fit = one numpy
  compare instead of a per-type Python loop.
- Requirement merging is skipped for pod-group signatures a node has
  already absorbed (union is idempotent).
- Rejections are cached per (pod-group signature, target, node version) —
  sound because a node's viable-type set and free resources only shrink.
  Topology-dependent rejections additionally key on the monotone counters
  that could flip them (per-constraint eligible-domain min counts,
  occupancy-set sizes): counts only grow, so a cached rejection stands
  until one of those counters moves.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Set, Tuple

import numpy as np

from ..apis import labels as L
from ..apis.objects import Pod
from ..apis.requirements import IN, Requirement, Requirements
from ..apis.resources import Resources
from ..cloudprovider.types import InstanceType, InstanceTypes
from .types import (
    ExistingNode,
    NewNodeClaim,
    NodePoolSpec,
    SchedulingSnapshot,
    SolveResult,
    Solver)


def pod_sig_digest(pod: Pod) -> str:
    """Digest of the pod-group signature — THE canonical tie-break shared
    by pod_sort_key and models.encoding.canonical_pod_groups. Both solvers'
    decision-identity depends on this being the single implementation."""
    dig = getattr(pod, "_sig_digest", None)
    if dig is None:
        dig = hashlib.md5(repr(pod_group_signature(pod)).encode()).hexdigest()
        pod._sig_digest = dig
    return dig


def pod_sort_key(pod: Pod) -> Tuple:
    """Canonical FFD order, shared verbatim by CPU and TPU solvers:
    descending resolved priority first (higher-priority pods pack before
    any lower tier can claim capacity — Kubernetes scheduling-queue
    semantics as a *packing order*, restriction-stable for subset
    gathers), then descending (cpu, memory), then *pod-group signature
    digest* so identical pods are contiguous within a size class
    (group-batched processing is then exactly per-pod FFD), then
    namespace/name. Priority is 0 unless PriorityClass objects exist,
    so priority-free clusters keep the historical order bit-for-bit."""
    r = pod.effective_requests()
    return (-getattr(pod, "priority", 0), -r["cpu"], -r["memory"],
            pod_sig_digest(pod), pod.metadata.namespace, pod.metadata.name)


def pod_group_signature(pod: Pod) -> Tuple:
    """Pods with equal signatures make identical scheduling demands.
    Memoized per pod (hot path: called in sort keys and group dedup)."""
    cached = getattr(pod, "_sig_cache", None)
    if cached is not None:
        return cached
    pod._sig_cache = sig = (
        tuple(sorted(pod.node_selector.items())),
        tuple(tuple(sorted(_term_items(t).items())) for t in pod.required_affinity_terms),
        tuple(sorted(pod.effective_requests().items())),
        tuple((t.key, t.operator, t.value, t.effect) for t in pod.tolerations),
        tuple((c.max_skew, c.topology_key, c.when_unsatisfiable, c.group)
              for c in pod.topology_spread),
        tuple((a.topology_key, a.group, a.anti, a.required) for a in pod.pod_affinity),
        pod.scheduling_group,
        # volume-topology constraints differ per pod even when selectors
        # match (each PVC pins its own zone)
        tuple(r for r in (getattr(pod, "_volume_reqs", None) or ())),
    )
    # resolved priority splits groups ONLY when nonzero: appended (never
    # inserted — positional consumers index sig[0..7]) so priority-free
    # clusters keep byte-identical signatures, digests, and fingerprints
    prio = getattr(pod, "priority", 0)
    if prio:
        pod._sig_cache = sig = sig + (("priority", prio),)
    return sig


def _term_items(term: Mapping) -> Dict:
    return {k: tuple(v) if isinstance(v, list) else v for k, v in term.items()}


def _min_values_floors(spec: NodePoolSpec) -> Dict[str, int]:
    """Pool-level minValues cardinality floors, memoized on the spec
    (karpenter.sh_nodepools.yaml:284; pods cannot carry minValues)."""
    fl = getattr(spec, "_mv_floors", None)
    if fl is None:
        fl = {r.key: r.min_values
              for r in spec.nodepool.scheduling_requirements()
              if r.min_values is not None}
        spec._mv_floors = fl
    return fl


def _mv_satisfied(types: Sequence[InstanceType], keep,
                  floors: Mapping[str, int]) -> bool:
    """True when the kept candidate types span >= floor distinct values for
    every floored key — core nodeclaim.Add's SatisfiesMinValues check; a
    placement that narrows candidates below a floor must be rejected."""
    card: Dict[str, Set[str]] = {k: set() for k in floors}
    for i, t in enumerate(types):
        if keep is not None and not keep[i]:
            continue
        for r in t.requirements:
            s = card.get(r.key)
            if s is not None and not r.complement:
                s.update(r.values)
    return all(len(card[k]) >= f for k, f in floors.items())


class _ResourceIndex:
    """Fixed resource-dimension universe for one solve."""

    def __init__(self, dims: Sequence[str]):
        self.dims = sorted(dims)
        self.pos = {d: i for i, d in enumerate(self.dims)}

    def vec(self, r: Resources) -> np.ndarray:
        v = np.zeros(len(self.dims), dtype=np.int64)
        for k, q in r.items():
            i = self.pos.get(k)
            if i is not None:
                v[i] = q
        return v

    def alloc_matrix(self, types: Sequence[InstanceType]) -> np.ndarray:
        m = np.zeros((len(types), len(self.dims)), dtype=np.int64)
        for row, t in enumerate(types):
            m[row] = self.vec(t.allocatable())
        return m


class _TopologyState:
    """Domain counters for spread + occupancy for (anti-)affinity. All
    counters are monotone non-decreasing within a solve."""

    def __init__(self, zones: Sequence[str]):
        self.zones = sorted(zones)
        self.spread: Dict[Tuple[str, str], Dict[str, int]] = {}
        self.occupancy: Dict[Tuple[str, str], Set[str]] = {}

    def count(self, group: str, key: str, domain: str) -> int:
        return self.spread.get((group, key), {}).get(domain, 0)

    def min_count(self, group: str, key: str, eligible: Sequence[str]) -> int:
        counts = self.spread.get((group, key), {})
        if not eligible:
            return 0
        return min(counts.get(d, 0) for d in eligible)

    def record(self, group: str, key: str, domain: str) -> None:
        bucket = self.spread.setdefault((group, key), {})
        bucket[domain] = bucket.get(domain, 0) + 1
        self.occupancy.setdefault((group, key), set()).add(domain)

    def occupied(self, group: str, key: str) -> Set[str]:
        return self.occupancy.get((group, key), set())


class _OpenNode:
    """A NodeClaim being built this round."""

    __slots__ = ("index", "spec", "requirements", "taints", "types", "alloc",
                 "pods", "requests", "requests_vec", "domains", "version",
                 "daemon_requests", "seen_sigs")

    def __init__(self, index: int, spec: NodePoolSpec,
                 requirements: Requirements, types: List[InstanceType],
                 alloc: np.ndarray, daemon_requests: Resources,
                 daemon_vec: np.ndarray):
        self.index = index
        self.spec = spec
        self.requirements = requirements
        self.taints = list(spec.nodepool.template.taints)
        self.types = types
        self.alloc = alloc
        self.pods: List[Pod] = []
        self.daemon_requests = daemon_requests
        self.requests = daemon_requests
        self.requests_vec = daemon_vec.copy()
        self.domains: Dict[str, str] = {}
        self.version = 0
        self.seen_sigs: Set[Tuple] = set()

    def hostname_domain(self) -> str:
        return f"new-node-{self.index}"


@dataclass
class _Placement:
    """A validated placement, ready to commit."""
    keep: Optional[np.ndarray] = None          # candidate-type row mask
    requirements: Optional[Requirements] = None
    types_override: Optional[List[InstanceType]] = None
    alloc_override: Optional[np.ndarray] = None
    fixed_domains: Dict[str, str] = field(default_factory=dict)
    records: List[Tuple[str, str, str]] = field(default_factory=list)


class _PodCtx:
    """Per-pod precomputed scheduling context (one per group signature)."""

    __slots__ = ("sig", "reqs", "requests", "vec", "topo_terms", "has_topo")

    def __init__(self, pod: Pod, rindex: _ResourceIndex):
        self.sig = pod_group_signature(pod)
        self.reqs = pod.scheduling_requirements()
        self.requests = pod.effective_requests()
        self.vec = rindex.vec(self.requests)
        self.has_topo = bool(pod.topology_spread) or bool(pod.pod_affinity) \
            or bool(pod.scheduling_group)


class CPUSolver(Solver):
    name = "cpu"

    def _solve_core(self, snapshot: SchedulingSnapshot,
                    pod_groups=None) -> SolveResult:
        # pod_groups intentionally unused: the oracle's own sort is its
        # independence from the grouped encoder it validates
        pods = sorted(snapshot.pods, key=pod_sort_key)
        zones = sorted(snapshot.zones) if snapshot.zones else \
            sorted({o.zone for np_ in snapshot.nodepools
                    for it in np_.instance_types for o in it.offerings})
        topo = _TopologyState(zones)

        dims = {"cpu", "memory", "pods"}
        for p in snapshot.pods:
            # effective requests carry derived dims too (the EBS
            # attachment slots from volume claims)
            dims.update(p.effective_requests().nonzero_keys())
        for d in snapshot.daemon_overheads:
            dims.update(d.requests.nonzero_keys())
        rindex = _ResourceIndex(dims)

        ctx_cache: Dict[Tuple, _PodCtx] = {}

        existing = sorted(snapshot.existing_nodes, key=lambda n: n.name)
        ex_used: Dict[str, Resources] = {n.name: n.used for n in existing}
        ex_version: Dict[str, int] = {n.name: 0 for n in existing}
        for node in existing:
            for group in node.pod_groups:
                zone = node.labels.get(L.ZONE)
                if zone:
                    topo.record(group, L.ZONE, zone)
                topo.record(group, L.HOSTNAME, node.name)

        nodepools = sorted(
            snapshot.nodepools,
            key=lambda s: (-s.nodepool.weight, s.nodepool.metadata.name))
        pool_usage: Dict[str, Resources] = {
            s.nodepool.metadata.name: s.in_use for s in nodepools}
        # (pool, sig) -> requirement-level admission (computed once per group)
        pool_admit: Dict[Tuple[str, Tuple], object] = {}

        open_nodes: List[_OpenNode] = []
        assignments: Dict[str, str] = {}
        unschedulable: Dict[str, str] = {}
        reject: Dict[Tuple, bool] = {}

        for pod in pods:
            ctx = ctx_cache.get(pod_group_signature(pod))
            if ctx is None:
                ctx = _PodCtx(pod, rindex)
                ctx_cache[ctx.sig] = ctx

            placed = False
            # 1) existing cluster nodes -----------------------------------
            for node in existing:
                ck = (ctx.sig, 0, node.name, ex_version[node.name],
                      self._topo_state_key(pod, topo) if ctx.has_topo else ())
                if ck in reject:
                    continue
                plan = self._try_existing(pod, ctx, node, ex_used[node.name], topo)
                if plan is None:
                    reject[ck] = True
                    continue
                ex_used[node.name] = ex_used[node.name] + ctx.requests
                ex_version[node.name] += 1
                for rec in plan.records:
                    topo.record(*rec)
                assignments[pod.full_name()] = node.name
                placed = True
                break
            if placed:
                continue
            # 2) open in-flight nodes -------------------------------------
            for node in open_nodes:
                ck = (ctx.sig, 1, node.index, node.version,
                      self._topo_state_key(pod, topo) if ctx.has_topo else ())
                if ck in reject:
                    continue
                plan = self._try_open(pod, ctx, node, topo, pool_usage)
                if plan is None:
                    reject[ck] = True
                    continue
                self._commit_open(node, pod, ctx, plan, topo, pool_usage)
                placed = True
                break
            if placed:
                continue
            # 3) a new node -----------------------------------------------
            reasons: List[str] = []
            for spec in nodepools:
                name = spec.nodepool.metadata.name
                reason = self._pool_blocked(spec, pool_usage, ctx)
                if reason:
                    reasons.append(f"{name}: {reason}")
                    continue
                node = self._try_new(pod, ctx, spec, len(open_nodes), snapshot,
                                     topo, pool_usage, pool_admit, rindex)
                if isinstance(node, str):
                    reasons.append(f"{name}: {node}")
                    continue
                open_nodes.append(node)
                placed = True
                break
            if not placed:
                unschedulable[pod.full_name()] = "; ".join(reasons) or "no nodepools"

        new_nodes = [self._finalize(n) for n in open_nodes]
        return SolveResult(new_nodes=new_nodes,
                           existing_assignments=assignments,
                           unschedulable=unschedulable)

    # -- rejection-cache topology key ----------------------------------
    def _topo_state_key(self, pod: Pod, topo: _TopologyState) -> Tuple:
        """The monotone counters a cached topology rejection depends on."""
        parts: List = []
        for c in pod.topology_spread:
            g = c.group or pod.scheduling_group
            if c.topology_key == L.HOSTNAME:
                parts.append(0)
            else:
                eligible = self._eligible_domains(c.topology_key, pod, topo)
                parts.append(topo.min_count(g, c.topology_key, eligible))
        for a in pod.pod_affinity:
            parts.append(len(topo.occupied(a.group, a.topology_key)))
        return tuple(parts)

    # ------------------------------------------------------------------
    def _try_existing(self, pod: Pod, ctx: _PodCtx, node: ExistingNode,
                      used: Resources, topo: _TopologyState) -> Optional[_Placement]:
        if not ctx.reqs.satisfied_by_labels(node.labels):
            return None
        if not all(t.tolerated_by(pod.tolerations) for t in node.taints):
            return None
        remaining = (node.allocatable - used).clamp_nonnegative()
        if not ctx.requests.fits(remaining):
            return None
        plan = _Placement()
        domain_of = {L.ZONE: node.labels.get(L.ZONE, ""), L.HOSTNAME: node.name}
        if not self._topology_ok_fixed(pod, domain_of, topo, plan):
            return None
        return plan

    def _try_open(self, pod: Pod, ctx: _PodCtx, node: _OpenNode,
                  topo: _TopologyState,
                  pool_usage: Dict[str, Resources]) -> Optional[_Placement]:
        limits = node.spec.nodepool.limits
        if limits is not None:
            used = pool_usage[node.spec.nodepool.metadata.name] + ctx.requests
            if any(used[res] > lim for res, lim in limits.items()):
                return None
        if not all(t.tolerated_by(pod.tolerations) for t in node.taints):
            return None

        if ctx.sig in node.seen_sigs:
            merged = node.requirements
            types, alloc = node.types, node.alloc
        else:
            merged = node.requirements.union(ctx.reqs)
            if any(r.unsatisfiable() for r in merged):
                return None
            if node.requirements.compatible(ctx.reqs):
                return None
            if merged == node.requirements:
                types, alloc = node.types, node.alloc
            else:
                keep_rows = [i for i, t in enumerate(node.types)
                             if not t.requirements.conflicts(merged)
                             and t.offerings.available().compatible(merged)]
                if not keep_rows:
                    return None
                types = [node.types[i] for i in keep_rows]
                alloc = node.alloc[keep_rows]

        new_vec = node.requests_vec + ctx.vec
        fit = (new_vec <= alloc).all(axis=1)
        if not fit.any():
            return None
        plan = _Placement(
            keep=fit,
            requirements=merged if merged is not node.requirements else None,
            types_override=types if types is not node.types else None,
            alloc_override=alloc if alloc is not node.alloc else None,
        )
        if not self._topology_ok_open(pod, node, merged, types, fit, topo, plan):
            return None
        floors = _min_values_floors(node.spec)
        if floors and not _mv_satisfied(types, plan.keep, floors):
            return None
        return plan

    def _try_new(self, pod: Pod, ctx: _PodCtx, spec: NodePoolSpec, index: int,
                 snapshot: SchedulingSnapshot, topo: _TopologyState,
                 pool_usage: Dict[str, Resources],
                 pool_admit: Dict[Tuple, object], rindex: _ResourceIndex):
        """Returns an _OpenNode or a string reason."""
        np_obj = spec.nodepool
        name = np_obj.metadata.name
        admit_key = (name, ctx.sig)
        admit = pool_admit.get(admit_key)
        if admit is None:
            admit = self._requirement_admission(pod, ctx, spec, snapshot, rindex)
            pool_admit[admit_key] = admit
        if isinstance(admit, str):
            return admit
        merged, types, alloc, daemon, daemon_vec = admit

        requests_vec = daemon_vec + ctx.vec
        fit = (requests_vec <= alloc).all(axis=1)
        if not fit.any():
            return "no instance types fit"
        node = _OpenNode(index, spec, merged,
                         [t for t, k in zip(types, fit) if k],
                         alloc[fit], daemon, daemon_vec)
        plan = _Placement(keep=np.ones(len(node.types), dtype=bool))
        if not self._topology_ok_open(pod, node, merged, node.types,
                                      plan.keep, topo, plan):
            return "topology constraints unsatisfiable"
        floors = _min_values_floors(spec)
        if floors and not _mv_satisfied(node.types, plan.keep, floors):
            return "minValues floors violated"
        self._commit_open(node, pod, ctx, plan, topo, pool_usage)
        return node

    def _requirement_admission(self, pod: Pod, ctx: _PodCtx,
                               spec: NodePoolSpec,
                               snapshot: SchedulingSnapshot,
                               rindex: _ResourceIndex):
        """Requirement-level admission of a pod group by a nodepool —
        everything about a (pool, group) pair that doesn't depend on counts."""
        np_obj = spec.nodepool
        base = np_obj.scheduling_requirements()
        offending = base.compatible(ctx.reqs)
        if offending:
            return f"incompatible requirements {offending}"
        if not all(t.tolerated_by(pod.tolerations)
                   for t in np_obj.template.taints):
            return "untolerated taints"
        merged = base.union(ctx.reqs)
        if any(r.unsatisfiable() for r in merged):
            return "empty requirement intersection"
        types = [t for t in spec.instance_types
                 if not t.requirements.conflicts(merged)
                 and t.offerings.available().compatible(merged)]
        if not types:
            return "no compatible instance types"
        daemon = self._daemon_requests(snapshot, merged)
        return (merged, types, rindex.alloc_matrix(types), daemon,
                rindex.vec(daemon))

    def _commit_open(self, node: _OpenNode, pod: Pod, ctx: _PodCtx,
                     plan: _Placement, topo: _TopologyState,
                     pool_usage: Dict[str, Resources]) -> None:
        node.version += 1
        node.pods.append(pod)
        node.requests = node.requests + ctx.requests
        node.requests_vec = node.requests_vec + ctx.vec
        types = plan.types_override if plan.types_override is not None else node.types
        alloc = plan.alloc_override if plan.alloc_override is not None else node.alloc
        if plan.keep is not None and not plan.keep.all():
            types = [t for t, k in zip(types, plan.keep) if k]
            alloc = alloc[plan.keep]
        node.types, node.alloc = types, alloc
        if plan.requirements is not None:
            # tightening preserves earlier sigs' idempotence (their reqs are
            # already absorbed into any superset)
            node.requirements = plan.requirements
        node.seen_sigs.add(ctx.sig)
        node.domains.update(plan.fixed_domains)
        for rec in plan.records:
            topo.record(*rec)
        pool = node.spec.nodepool.metadata.name
        pool_usage[pool] = pool_usage[pool] + ctx.requests

    # -- topology ------------------------------------------------------
    def _topology_ok_fixed(self, pod: Pod, domain_of: Mapping[str, str],
                           topo: _TopologyState, plan: _Placement) -> bool:
        group = pod.scheduling_group
        for c in pod.topology_spread:
            if c.when_unsatisfiable != "DoNotSchedule":
                continue
            domain = domain_of.get(c.topology_key, "")
            if not domain:
                return False
            g = c.group or group
            if c.topology_key == L.HOSTNAME:
                min_count = 0  # a fresh node is always a hypothetical domain
            else:
                eligible = self._eligible_domains(c.topology_key, pod, topo)
                min_count = topo.min_count(g, c.topology_key, eligible)
            if topo.count(g, c.topology_key, domain) + 1 - min_count > c.max_skew:
                return False
        for a in pod.pod_affinity:
            if not a.required:
                continue
            domain = domain_of.get(a.topology_key, "")
            occupied = topo.occupied(a.group, a.topology_key)
            if a.anti:
                if domain in occupied:
                    return False
            else:
                if occupied:
                    if domain not in occupied:
                        return False
                elif a.group != group:
                    return False  # required affinity to a group with no pods
        for c in pod.topology_spread:
            g = c.group or group
            d = domain_of.get(c.topology_key, "")
            if g and d:
                plan.records.append((g, c.topology_key, d))
        if group:
            self._record_membership(pod, domain_of, plan)
        return True

    def _topology_ok_open(self, pod: Pod, node: _OpenNode,
                          merged: Requirements, types: Sequence[InstanceType],
                          fit: np.ndarray, topo: _TopologyState,
                          plan: _Placement) -> bool:
        group = pod.scheduling_group
        zone_needed = any(c.topology_key == L.ZONE for c in pod.topology_spread) \
            or any(a.topology_key == L.ZONE for a in pod.pod_affinity if a.required)
        domain_of: Dict[str, str] = {L.HOSTNAME: node.hostname_domain()}
        if L.ZONE in node.domains:
            domain_of[L.ZONE] = node.domains[L.ZONE]
        elif zone_needed:
            fit_types = [t for t, k in zip(types, fit) if k]
            chosen = self._choose_zone(pod, merged, fit_types, topo)
            if chosen is None:
                return False
            domain_of[L.ZONE] = chosen
            plan.fixed_domains[L.ZONE] = chosen
            narrowed_reqs = (plan.requirements or merged).add(
                Requirement.new(L.ZONE, IN, [chosen]))
            keep = np.array([
                bool(k) and not t.requirements.conflicts(narrowed_reqs)
                and bool(t.offerings.available().compatible(narrowed_reqs))
                for t, k in zip(types, fit)], dtype=bool)
            if not keep.any():
                return False
            plan.keep = keep
            plan.requirements = narrowed_reqs
        return self._topology_ok_fixed(pod, domain_of, topo, plan)

    def _choose_zone(self, pod: Pod, merged: Requirements,
                     types: Sequence[InstanceType],
                     topo: _TopologyState) -> Optional[str]:
        """Min-count eligible zone, lexicographic tie-break (deterministic)."""
        zone_req = merged.get(L.ZONE)
        candidates = sorted({
            o.zone for t in types for o in t.offerings.available()
            if zone_req is None or zone_req.has(o.zone)})
        group = pod.scheduling_group
        best, best_key = None, None
        for z in candidates:
            ok = True
            score = 0
            for c in pod.topology_spread:
                if c.topology_key != L.ZONE or c.when_unsatisfiable != "DoNotSchedule":
                    continue
                g = c.group or group
                eligible = self._eligible_domains(L.ZONE, pod, topo)
                if topo.count(g, L.ZONE, z) + 1 \
                        - topo.min_count(g, L.ZONE, eligible) > c.max_skew:
                    ok = False
                    break
                score += topo.count(g, L.ZONE, z)
            if not ok:
                continue
            for a in pod.pod_affinity:
                if not a.required or a.topology_key != L.ZONE:
                    continue
                occupied = topo.occupied(a.group, L.ZONE)
                if a.anti and z in occupied:
                    ok = False
                    break
                if not a.anti and occupied and z not in occupied:
                    ok = False
                    break
                if not a.anti and not occupied and a.group != group:
                    ok = False
                    break
            if not ok:
                continue
            key = (score, z)
            if best_key is None or key < best_key:
                best, best_key = z, key
        return best

    def _eligible_domains(self, key: str, pod: Pod,
                          topo: _TopologyState) -> List[str]:
        if key == L.ZONE:
            zone_req = pod.scheduling_requirements().get(L.ZONE)
            return [z for z in topo.zones if zone_req is None or zone_req.has(z)]
        return []

    def _record_membership(self, pod: Pod, domain_of: Mapping[str, str],
                           plan: _Placement) -> None:
        group = pod.scheduling_group
        if not group:
            return
        seen = {(g, k) for (g, k, _) in plan.records}
        for key in (L.ZONE, L.HOSTNAME):
            d = domain_of.get(key, "")
            if d and (group, key) not in seen:
                plan.records.append((group, key, d))

    # -- pools / daemons / finalize ------------------------------------
    @staticmethod
    def _pool_blocked(spec: NodePoolSpec, usage: Dict[str, Resources],
                      ctx: _PodCtx) -> str:
        limits = spec.nodepool.limits
        if limits is None:
            return ""
        used = usage[spec.nodepool.metadata.name] + ctx.requests
        for res, lim in limits.items():
            if used[res] > lim:
                return f"limit reached for {res}"
        return ""

    def _daemon_requests(self, snapshot: SchedulingSnapshot,
                         node_reqs: Requirements) -> Resources:
        total = Resources()
        for d in snapshot.daemon_overheads:
            if not node_reqs.compatible(d.requirements):
                total = total + d.requests
        return total

    @staticmethod
    def _finalize(node: _OpenNode) -> NewNodeClaim:
        reqs = node.requirements
        ordered = InstanceTypes(node.types).order_by_price(reqs)
        return NewNodeClaim(
            nodepool=node.spec.nodepool.metadata.name,
            requirements=reqs,
            pod_names=sorted(p.full_name() for p in node.pods),
            instance_type_names=[t.name for t in ordered],
            requests=node.requests,
            taints=node.taints,
        )


def reqs_satisfied_by_node_labels(reqs: Requirements,
                                  labels: Mapping[str, str]) -> bool:
    return reqs.satisfied_by_labels(labels)
