"""Kubernetes-events analog: the operational surface `kubectl describe`
shows. Mirrors pkg/cloudprovider/events/events.go (NodePool/NodeClaim
failed-to-resolve-NodeClass) and pkg/controllers/interruption/events
(SpotInterrupted, RebalanceRecommendation, Stopping/Terminating) — the
reference publishes through a record.EventRecorder; here a bounded ring
buffer plays the API server's role so tests and the daemon can assert on
and expose what happened."""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, List, Optional

NORMAL = "Normal"
WARNING = "Warning"


@dataclass(frozen=True)
class Event:
    kind: str          # involved object kind (NodeClaim/NodePool/Node/...)
    name: str          # involved object name
    type: str          # Normal | Warning
    reason: str        # machine-readable camel-case reason
    message: str
    timestamp: float = field(default=0.0, compare=False)


class Recorder:
    def __init__(self, clock=time.time, capacity: int = 1000):
        self._clock = clock
        self._mu = threading.Lock()
        self._events: Deque[Event] = deque(maxlen=capacity)

    def publish(self, kind: str, name: str, reason: str, message: str,
                type: str = NORMAL) -> Event:
        ev = Event(kind=kind, name=name, type=type, reason=reason,
                   message=message, timestamp=self._clock())
        with self._mu:
            self._events.append(ev)
        return ev

    # -- reads ----------------------------------------------------------
    def events(self, kind: Optional[str] = None,
               name: Optional[str] = None,
               reason: Optional[str] = None) -> List[Event]:
        with self._mu:
            out = list(self._events)
        if kind is not None:
            out = [e for e in out if e.kind == kind]
        if name is not None:
            out = [e for e in out if e.name == name]
        if reason is not None:
            out = [e for e in out if e.reason == reason]
        return out

    def reasons(self) -> List[str]:
        with self._mu:
            return [e.reason for e in self._events]


# -- reference event constructors (events.go shapes) ------------------------

def spot_interrupted(recorder: Recorder, claim_name: str) -> None:
    recorder.publish("NodeClaim", claim_name, "SpotInterrupted",
                     f"NodeClaim {claim_name} event: A spot interruption "
                     "warning was triggered for the node", WARNING)


def rebalance_recommendation(recorder: Recorder, claim_name: str) -> None:
    recorder.publish("NodeClaim", claim_name, "SpotRebalanceRecommendation",
                     f"NodeClaim {claim_name} event: A spot rebalance "
                     "recommendation was triggered for the node", NORMAL)


def instance_stopping(recorder: Recorder, claim_name: str) -> None:
    recorder.publish("NodeClaim", claim_name, "InstanceStopping",
                     f"NodeClaim {claim_name} event: Instance is stopping",
                     WARNING)


def instance_terminating(recorder: Recorder, claim_name: str) -> None:
    recorder.publish("NodeClaim", claim_name, "InstanceTerminating",
                     f"NodeClaim {claim_name} event: Instance is terminating",
                     WARNING)


def terminating_on_interruption(recorder: Recorder, claim_name: str) -> None:
    recorder.publish("NodeClaim", claim_name, "TerminatingOnInterruption",
                     f"Interruption triggered termination for the NodeClaim "
                     f"{claim_name}", WARNING)


def failed_resolving_nodeclass(recorder: Recorder, kind: str,
                               name: str, nodeclass: str) -> None:
    recorder.publish(kind, name, "FailedResolvingNodeClass",
                     f"Failed resolving EC2NodeClass {nodeclass}", WARNING)


def launch_failed(recorder: Recorder, claim_name: str, message: str) -> None:
    recorder.publish("NodeClaim", claim_name, "LaunchFailed", message,
                     WARNING)
