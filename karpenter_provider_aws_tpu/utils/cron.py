"""Upstream-cronjob-syntax schedules for disruption budgets.

A budget with ``schedule`` + ``duration`` is active from each schedule
firing until ``firing + duration`` (core NodePool budget semantics —
the reference documents the syntax and the no-timezone rule in
karpenter.sh_nodepools.yaml:126-133). Times are naive UTC, matching
"Timezones are not supported".

Five standard fields (minute hour day-of-month month day-of-week) plus
the @-shortcuts. The classic cron quirk is preserved: when BOTH
day-of-month and day-of-week are restricted, a day matches if EITHER
does.
"""

from __future__ import annotations

import re
from datetime import datetime, timedelta, timezone
from typing import Optional, Set, Tuple

_SHORTCUTS = {
    "@annually": "0 0 1 1 *",
    "@yearly": "0 0 1 1 *",
    "@monthly": "0 0 1 * *",
    "@weekly": "0 0 * * 0",
    "@daily": "0 0 * * *",
    "@midnight": "0 0 * * *",
    "@hourly": "0 * * * *",
}

_RANGES = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 6))

_MONTH_NAMES = {n: i + 1 for i, n in enumerate(
    "jan feb mar apr may jun jul aug sep oct nov dec".split())}
_DOW_NAMES = {n: i for i, n in enumerate(
    "sun mon tue wed thu fri sat".split())}


class CronError(ValueError):
    pass


def _parse_field(spec: str, lo: int, hi: int, names) -> Tuple[Set[int], bool]:
    """-> (allowed values, was-unrestricted)."""
    if spec == "*":
        return set(range(lo, hi + 1)), True
    out: Set[int] = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            if not step_s.isdigit() or int(step_s) < 1:
                raise CronError(f"invalid step {step_s!r}")
            step = int(step_s)
        if part == "*":
            a, b = lo, hi
        elif "-" in part:
            a_s, b_s = part.split("-", 1)
            a, b = _parse_value(a_s, names), _parse_value(b_s, names)
        else:
            a = b = _parse_value(part, names)
            if step > 1:  # "5/15" means 5-hi/15 in cron
                b = hi
        if not (lo <= a <= hi and lo <= b <= hi and a <= b):
            raise CronError(f"value out of range in {spec!r}")
        out.update(range(a, b + 1, step))
    return out, False


def _parse_value(s: str, names) -> int:
    s = s.strip().lower()
    if names and s in names:
        return names[s]
    if not s.isdigit():
        raise CronError(f"invalid value {s!r}")
    v = int(s)
    if names is _DOW_NAMES and v == 7:  # both 0 and 7 mean Sunday
        return 0
    return v


class Cron:
    def __init__(self, expr: str):
        expr = expr.strip()
        expr = _SHORTCUTS.get(expr.lower(), expr)
        fields = expr.split()
        if len(fields) != 5:
            raise CronError(f"expected 5 fields, got {len(fields)}: {expr!r}")
        namemaps = (None, None, None, _MONTH_NAMES, _DOW_NAMES)
        parsed = [_parse_field(f, lo, hi, nm)
                  for f, (lo, hi), nm in zip(fields, _RANGES, namemaps)]
        (self.minutes, _), (self.hours, _) = parsed[0], parsed[1]
        (self.doms, self.dom_star) = parsed[2]
        (self.months, _) = parsed[3]
        (self.dows, self.dow_star) = parsed[4]
        self._minutes_desc = sorted(self.minutes, reverse=True)
        self._hours_desc = sorted(self.hours, reverse=True)

    def _day_matches(self, d) -> bool:
        if d.month not in self.months:
            return False
        dow = (d.weekday() + 1) % 7  # python Mon=0 -> cron Sun=0
        dom_ok = d.day in self.doms
        dow_ok = dow in self.dows
        if not self.dom_star and not self.dow_star:
            return dom_ok or dow_ok  # the classic either-matches quirk
        return dom_ok and dow_ok

    def most_recent_fire(self, now: float) -> Optional[float]:
        """Unix time of the latest firing <= ``now`` (naive UTC), or
        None if none in the past 366 days (cannot happen for a valid
        spec, which fires at least yearly)."""
        t = datetime.fromtimestamp(now, tz=timezone.utc)
        for day_off in range(367):
            d = (t - timedelta(days=day_off)).date()
            if not self._day_matches(d):
                continue
            max_h = t.hour if day_off == 0 else 23
            for h in self._hours_desc:
                if h > max_h:
                    continue
                max_m = t.minute if day_off == 0 and h == t.hour else 59
                for m in self._minutes_desc:
                    if m <= max_m:
                        return datetime(
                            d.year, d.month, d.day, h, m,
                            tzinfo=timezone.utc).timestamp()
        return None


_DUR_RE = re.compile(r"^(?:(\d+)h)?(?:(\d+)m)?(?:0s)?$")


def parse_duration(d) -> Optional[float]:
    """Budget duration -> seconds. Accepts float seconds (the model's
    native type) or the CRD's go-duration subset ("8h", "30m",
    "1h30m" — karpenter.sh_nodepools.yaml duration pattern)."""
    if d is None:
        return None
    if isinstance(d, (int, float)):
        return float(d)
    m = _DUR_RE.match(d.strip())
    if not m or not (m.group(1) or m.group(2)):
        raise CronError(f"invalid duration {d!r}")
    return float(int(m.group(1) or 0) * 3600 + int(m.group(2) or 0) * 60)
