"""Debug watch-controllers: log node / nodeclaim / pod transitions.

The analog of the reference's E2E debug watchers
(test/pkg/debug/{node,nodeclaim,pod}.go): informer-backed loggers that
print every state transition of the interesting kinds — what changed,
from what, to what — so a stuck rollout or a runaway reconcile loop is
visible in the log stream. Attach to any FakeKube (the daemon wires them
under --log-level DEBUG; tests attach them ad hoc when debugging)."""

from __future__ import annotations

import logging
import queue
import threading
from collections import deque
from typing import Callable, Dict, Optional

from ..fake.kube import Event, FakeKube

log = logging.getLogger("karpenter.debug")

_WATCHED_KINDS = ("Node", "NodeClaim", "Pod")


def _fingerprint(obj) -> Dict[str, object]:
    """The transition-relevant state per kind (node.go/nodeclaim.go/pod.go
    each log their own field set)."""
    kind = obj.kind
    if kind == "Node":
        return {"ready": obj.ready,
                "taints": sorted(t.key for t in obj.taints)}
    if kind == "NodeClaim":
        return {"launched": obj.launched, "registered": obj.registered,
                "initialized": obj.initialized,
                "deleting": obj.metadata.deletion_timestamp is not None,
                "node": obj.node_name}
    if kind == "Pod":
        return {"phase": obj.phase, "node": obj.node_name}
    return {}


class TransitionWatcher:
    """Observes kube watch events and logs only real transitions (the
    reference's watchers diff the informer's old/new objects).

    Events carry live object references, so fingerprints MUST be taken at
    event time — a deferred drain would see every event's object in its
    final state and miss the intermediate transitions. The watch queues'
    ``put`` is therefore shadowed with an eager observer; ``drain`` is a
    cheap no-op hook for reconcile-loop registration."""

    def __init__(self, kube: FakeKube, kinds=_WATCHED_KINDS,
                 sink: Optional[Callable[[str], None]] = None):
        self.kube = kube
        self.kinds = tuple(kinds)
        self.sink = sink or (lambda line: log.debug("%s", line))
        self._last: Dict[str, Dict] = {}
        self._mu = threading.Lock()
        #: recent transitions for test assertions — bounded so a
        #: long-running daemon under churn never grows without limit
        #: (the log stream is the durable record)
        self.transitions: deque = deque(maxlen=10_000)
        for k in self.kinds:
            q = kube.watch(k)
            while True:      # observe the initial-list replay eagerly too
                try:
                    self._observe(q.get_nowait())
                except queue.Empty:
                    break
            q.put = self._observe  # type: ignore[method-assign]

    def drain(self) -> int:
        """Transitions observed so far (observation itself is eager)."""
        with self._mu:
            return len(self.transitions)

    def _observe(self, ev: Event) -> int:
        obj = ev.obj
        key = f"{obj.kind}/{obj.metadata.namespace or ''}/{obj.metadata.name}"
        with self._mu:
            if ev.type == "DELETED":
                self._last.pop(key, None)
                line = f"{key} DELETED"
                self.transitions.append(line)
                self.sink(line)
                return 1
            now = _fingerprint(obj)
            before = self._last.get(key)
            self._last[key] = now
            if before == now:
                return 0  # resync noise, not a transition
            delta = {k: (None if before is None else before.get(k), v)
                     for k, v in now.items()
                     if before is None or before.get(k) != v}
            line = f"{key} {ev.type} " + " ".join(
                f"{k}:{a}->{b}" for k, (a, b) in sorted(delta.items()))
            self.transitions.append(line)
            self.sink(line)
            return 1


def attach(kube: FakeKube, sink=None) -> TransitionWatcher:
    """Convenience: one watcher over all interesting kinds."""
    return TransitionWatcher(kube, sink=sink)
