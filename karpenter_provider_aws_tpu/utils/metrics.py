"""Minimal Prometheus-shaped metrics registry.

The reference emits 101 documented metrics in 20 groups
(website docs/reference/metrics.md); this registry backs the subset the
rebuilt controllers emit (scheduling duration/queue depth, interruption
counters, batcher sizes, provider gauges) with the same names.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Mapping, Optional, Tuple


def _lk(labels: Optional[Mapping[str, str]]) -> Tuple:
    return tuple(sorted((labels or {}).items()))


class Metrics:
    def __init__(self):
        self._mu = threading.Lock()
        self.counters: Dict[Tuple[str, Tuple], float] = {}
        self.gauges: Dict[Tuple[str, Tuple], float] = {}
        self.histograms: Dict[Tuple[str, Tuple], List[float]] = {}

    def inc(self, name: str, value: float = 1.0,
            labels: Optional[Mapping[str, str]] = None) -> None:
        with self._mu:
            key = (name, _lk(labels))
            self.counters[key] = self.counters.get(key, 0.0) + value

    def set_gauge(self, name: str, value: float,
                  labels: Optional[Mapping[str, str]] = None) -> None:
        with self._mu:
            self.gauges[(name, _lk(labels))] = value

    def observe(self, name: str, value: float,
                labels: Optional[Mapping[str, str]] = None) -> None:
        with self._mu:
            self.histograms.setdefault((name, _lk(labels)), []).append(value)

    def clear_series(self, name: str,
                     match: Optional[Mapping[str, str]] = None) -> None:
        """Drop labeled series of a gauge (full re-emit pattern: series
        for entities that vanished must not linger stale). With `match`,
        only series whose labels contain that subset are dropped."""
        with self._mu:
            want = set((match or {}).items())
            for key in [k for k in self.gauges
                        if k[0] == name and want <= set(k[1])]:
                del self.gauges[key]

    # -- reads -----------------------------------------------------------
    def counter(self, name: str, labels: Optional[Mapping[str, str]] = None) -> float:
        return self.counters.get((name, _lk(labels)), 0.0)

    def gauge(self, name: str, labels: Optional[Mapping[str, str]] = None) -> float:
        return self.gauges.get((name, _lk(labels)), 0.0)

    def percentile(self, name: str, q: float,
                   labels: Optional[Mapping[str, str]] = None) -> float:
        vals = sorted(self.histograms.get((name, _lk(labels)), []))
        if not vals:
            return 0.0
        return vals[min(len(vals) - 1, int(len(vals) * q))]

    def render(self) -> str:
        """Prometheus exposition-format-ish dump. Locked: the daemon's
        HTTP threads scrape concurrently with reconciling controllers."""
        with self._mu:
            counters = sorted(self.counters.items())
            gauges = sorted(self.gauges.items())
            histograms = [(k, (len(v), sum(v)))
                          for k, v in sorted(self.histograms.items())]
        lines = []
        for (name, labels), v in counters:
            lines.append(f"{name}{_fmt(labels)} {v}")
        for (name, labels), v in gauges:
            lines.append(f"{name}{_fmt(labels)} {v}")
        for (name, labels), (cnt, total) in histograms:
            lines.append(f"{name}_count{_fmt(labels)} {cnt}")
            lines.append(f"{name}_sum{_fmt(labels)} {total}")
        return "\n".join(lines) + "\n"


def _fmt(labels: Tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"
