"""Multi-host distributed mesh: cross-process dp x tp solving.

The distributed tier over parallel/mesh.py: N OS processes (in CI, N
subprocesses x ``XLA_FLAGS=--xla_force_host_platform_device_count=K``
virtual CPU devices; on real hardware, one process per host) form ONE
logical 2-D ``("dp","tp")`` mesh via ``jax.distributed.initialize``.
The pods (slot) axis shards across processes — each host owns only its
dp rows of the slot-indexed tables and commits them with
``jax.make_array_from_single_device_arrays``, so no host ever
materializes the full arena. The compiled program is the SAME
``_solve_sharded2`` shard_map kernel PR 8 runs on one process; only the
mesh underneath changes, so decisions stay identical by construction.

Device ordering is the load-bearing subtlety: device ids are NOT
sequential across processes (a 2-process CPU run hands out ids like
0..7 and 131072..131079), so the global mesh orders devices
PROCESS-MAJOR — ``sorted(jax.devices(), key=(process_index, id))`` —
and dp is constrained to a multiple of the process count. Together
those make every dp row live inside one process, which means:

- each process's addressable shard of a slot-sharded table is one
  contiguous run of global slot rows (``local_slot_rows``), and
- the per-scan-step collective bill (docs/solver-design.md) splits
  cleanly: the (1+P) tp-axis pmax reductions stay intra-process, while
  the (P+1) dp all_gathers and 2 dp psums cross process boundaries —
  (P+3) cross-host collectives per scan step, each O(dp) scalars,
  latency-dominated and constant in the slot count.

Control plane (the ``fleet.meshgroup`` coordinator) rides a separate
loopback TCP protocol (length-prefixed JSON header + npz payload):
workers run :func:`run_worker` loops; the SPMD data plane is jax's own
distributed runtime. Two input modes keep "no full arena on any host"
honest: ``solve_seeded`` regenerates each host's slab from (seed,
tick) — zero bulk bytes on the wire — and ``solve_frame`` ships each
worker only its slab slices.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import socket
import struct
import time
from typing import Dict, NamedTuple, Optional, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from .mesh import (AXIS, AXIS_DP, _default_dp, _input_specs2, _out_dict,
                   _prep_field, _resolve_sum_only, _solve_sharded2)
from ..ops.ffd_jax import KernelInputs

log = logging.getLogger(__name__)

#: env contract (chart: deploy/chart/templates/solver-mesh-workers.yaml)
COORDINATOR_ENV = "SOLVER_DISTMESH_COORDINATOR"
PROCESSES_ENV = "SOLVER_DISTMESH_PROCESSES"
PROCESS_ID_ENV = "SOLVER_DISTMESH_PROCESS_ID"
LOCAL_DEVICES_ENV = "SOLVER_DISTMESH_LOCAL_DEVICES"
WORKERS_ENV = "SOLVER_DISTMESH_WORKERS"

#: the fields a warm tick rewrites (fleet ticks mutate demand and the
#: existing nodes' usage; catalog/feasibility stay resident on-device)
DIRTY_FIELDS = ("n", "ex_used0")


class DistConfig(NamedTuple):
    """One process's identity in the distributed job."""
    coordinator: str       # "host:port" of jax's coordinator service
    num_processes: int
    process_id: int
    #: virtual CPU devices per process (CI/localhost mode); None means
    #: use the real local backend untouched
    local_devices: Optional[int] = None


def config_from_env() -> Optional[DistConfig]:
    """DistConfig from the chart's env contract, or None when unset.
    The process id falls back to the StatefulSet ordinal parsed from
    POD_NAME (+1: the coordinator sidecar is process 0, worker ordinal
    i is process i+1)."""
    coord = os.environ.get(COORDINATOR_ENV)
    if not coord:
        return None
    nproc = int(os.environ.get(PROCESSES_ENV) or
                (int(os.environ.get(WORKERS_ENV, "0")) + 1))
    pid_env = os.environ.get(PROCESS_ID_ENV)
    if pid_env is not None:
        pid = int(pid_env)
    else:
        pod = os.environ.get("POD_NAME", "")
        tail = pod.rsplit("-", 1)[-1]
        pid = int(tail) + 1 if tail.isdigit() else 0
    local = os.environ.get(LOCAL_DEVICES_ENV)
    return DistConfig(coord, nproc, pid,
                      int(local) if local else None)


_INITIALIZED: Optional[DistConfig] = None


def init_process(cfg: DistConfig) -> None:
    """Join the distributed job (idempotent per process). In virtual-
    device mode the device-count flag and the CPU platform pin must land
    before the first backend init (read once, at client creation), and
    cross-process CPU collectives need the gloo implementation — the
    default shared-memory transport cannot cross process boundaries."""
    global _INITIALIZED
    if _INITIALIZED is not None:
        if _INITIALIZED != cfg:
            raise RuntimeError(
                f"distmesh already initialized as {_INITIALIZED}, "
                f"refusing re-init as {cfg}")
        return
    if cfg.local_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count"
                f"={cfg.local_devices}").strip()
        jax.config.update("jax_platforms", "cpu")
        if cfg.num_processes > 1:
            # gloo only under a real distributed job: the gloo factory
            # requires the distributed client, so a single-process
            # backend init with it configured fails outright
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
    if cfg.num_processes > 1:
        jax.distributed.initialize(coordinator_address=cfg.coordinator,
                                   num_processes=cfg.num_processes,
                                   process_id=cfg.process_id)
    _INITIALIZED = cfg
    log.info("distmesh: process %d/%d joined (coordinator %s)",
             cfg.process_id, cfg.num_processes, cfg.coordinator)


def global_devices():
    """Every device in the job, PROCESS-MAJOR. Never rely on raw device
    ids for ordering — they are backend-assigned and non-sequential
    across processes (module docstring)."""
    return sorted(jax.devices(), key=lambda d: (d.process_index, d.id))


def dist_dp(ndev: int, nproc: int) -> int:
    """dp extent for the distributed 2-D mesh: a multiple of the
    process count (so every dp row lives inside one process — the
    contiguous-slab + intra-process-tp-pmax invariant) that divides the
    device count. Default: nproc x the single-process default for the
    per-process device share. KARP_DIST_DP overrides when valid."""
    env = os.environ.get("KARP_DIST_DP")
    if env:
        try:
            v = int(env)
        except ValueError:
            v = 0
        if v >= nproc and v % nproc == 0 and ndev % v == 0:
            return v
        log.warning("KARP_DIST_DP=%r invalid for %d devices / %d "
                    "processes; using default", env, ndev, nproc)
    if ndev % nproc:
        raise ValueError(
            f"{ndev} devices do not split evenly over {nproc} processes")
    return nproc * _default_dp(ndev // nproc)


def dist_mesh2(devices=None, dp: Optional[int] = None) -> Mesh:
    """The global 2-D ``("dp","tp")`` mesh over every process's
    devices, process-major so dp rows are process-contiguous."""
    if devices is None:
        devices = global_devices()
    ndev = len(devices)
    if dp is None:
        dp = dist_dp(ndev, jax.process_count())
    if dp < 1 or ndev % dp:
        raise ValueError(f"dp={dp} does not divide {ndev} devices")
    return Mesh(np.asarray(devices).reshape(dp, ndev // dp),
                axis_names=(AXIS_DP, AXIS))


def local_slot_rows(Np: int, nproc: int, pid: int) -> Tuple[int, int]:
    """The contiguous run [lo, hi) of PADDED global slot rows process
    ``pid`` owns. Holds because dp is a multiple of nproc, Np a
    multiple of dp, and the mesh is process-major."""
    if Np % nproc:
        raise ValueError(f"Np={Np} not a multiple of nproc={nproc}")
    rows = Np // nproc
    return pid * rows, (pid + 1) * rows


def slab_rows(n_max: int, E: int, mesh: Mesh) -> Tuple[int, int, int]:
    """(Np, lo, hi) for this process's slab of the solve's slot axis:
    Np is the dp-padded slot range (parallel/mesh._pad_slots), [lo, hi)
    the rows this process commits."""
    ndp = mesh.shape[AXIS_DP]
    N = E + n_max
    Np = ((N + ndp - 1) // ndp) * ndp
    lo, hi = local_slot_rows(Np, jax.process_count(), jax.process_index())
    return Np, lo, hi


class LocalSlab(NamedTuple):
    """A host-local slab of a globally slot-sharded array: rows
    [lo, hi) along ``axis`` of a logical array of ``global_shape``.
    The slab already spans the PADDED slot range (rows past the true
    table are zeros), so commit needs no further prep."""
    array: np.ndarray
    lo: int
    hi: int
    axis: int
    global_shape: Tuple[int, ...]


def commit_global(x, mesh: Mesh, spec: PS):
    """Commit one logical array onto the global mesh from per-process
    pieces: slice out each ADDRESSABLE device's shard, device_put it
    locally, and assemble with make_array_from_single_device_arrays —
    the multi-process construction where plain device_put would demand
    the (unaddressable) remote devices. Accepts a full ndarray or a
    LocalSlab; a slab is remapped from global to slab-local rows and
    refuses indices outside this host's ownership (which would mean the
    mesh/slab geometry drifted)."""
    sh = NamedSharding(mesh, spec)
    if isinstance(x, LocalSlab):
        gshape = tuple(int(s) for s in x.global_shape)
        arr = np.asarray(x.array)
        idx_map = sh.addressable_devices_indices_map(gshape)
        shards, devs = [], []
        for d, idx in idx_map.items():
            idx = list(idx)
            sl = idx[x.axis]
            start = sl.start or 0
            stop = gshape[x.axis] if sl.stop is None else sl.stop
            if start < x.lo or stop > x.hi:
                raise ValueError(
                    f"device {d.id} wants global rows [{start},{stop}) "
                    f"outside local slab [{x.lo},{x.hi})")
            idx[x.axis] = slice(start - x.lo, stop - x.lo)
            shards.append(jax.device_put(arr[tuple(idx)], d))
            devs.append(d)
        return jax.make_array_from_single_device_arrays(
            gshape, sh, shards)
    arr = np.asarray(x)
    idx_map = sh.addressable_devices_indices_map(arr.shape)
    shards = [jax.device_put(arr[idx], d) for d, idx in idx_map.items()]
    return jax.make_array_from_single_device_arrays(
        arr.shape, sh, shards)


def collective_bill(P: int, dp: int, nproc: int, G: int) -> dict:
    """The analytic per-scan-step collective bill for the distributed
    2-D kernel (docs/solver-design.md), split at the process boundary.
    tp-axis pmax reductions stay intra-process (dp rows are process-
    contiguous); every dp-axis collective crosses hosts when nproc>1.
    Each dp collective moves O(dp) scalars — latency, not bandwidth."""
    cross = (P + 1) + 2 if nproc > 1 else 0
    return {
        "steps": G,
        "per_step": {"tp_pmax": 1 + P, "dp_all_gather": P + 1,
                     "dp_psum": 2},
        "cross_process_per_step": cross,
        "cross_process_total": cross * G,
        "bytes_per_dp_collective": 8 * dp,
    }


# -- the deterministic tick harness ----------------------------------------

_M64 = (1 << 64) - 1


def _hash_u64(x):
    """splitmix64 over uint64 (vectorized): the slab-parity generator
    primitive — value at global index i depends only on i and the
    stream key, so generating rows [lo, hi) equals slicing a full
    generation. Counter-based by construction (unlike a seeded RNG
    stream, which would force every host to draw the whole arena)."""
    with np.errstate(over="ignore"):
        x = (np.asarray(x, np.uint64) + np.uint64(0x9E3779B97F4A7C15)) \
            & np.uint64(_M64)
        z = x
        z = ((z ^ (z >> np.uint64(30))) *
             np.uint64(0xBF58476D1CE4E5B9)) & np.uint64(_M64)
        z = ((z ^ (z >> np.uint64(27))) *
             np.uint64(0x94D049BB133111EB)) & np.uint64(_M64)
        return z ^ (z >> np.uint64(31))


def _field_key(seed: int, tick: int, field: str) -> int:
    """Stream key per field. Only DIRTY_FIELDS mix the tick in: every
    other field must be bit-identical across ticks or the dirty-list
    patch contract (parallel/mesh._place_resident) would be a lie."""
    t = tick if field in DIRTY_FIELDS else 0
    h = hashlib.sha256(f"{seed}:{t}:{field}".encode()).digest()
    return int.from_bytes(h[:8], "big")


def _gen(seed, tick, field, rows, cols, lo=0):
    """uint64 grid for global rows [lo, lo+rows) x cols of ``field``."""
    key = np.uint64(_field_key(seed, tick, field))
    idx = (np.arange(lo, lo + rows, dtype=np.uint64)[:, None] *
           np.uint64(max(cols, 1)) +
           np.arange(cols, dtype=np.uint64)[None, :])
    with np.errstate(over="ignore"):
        return _hash_u64(idx + key)


def tick_arrays(shape: Dict[str, int], seed: int, tick: int,
                slab: Optional[Tuple[int, int, int]] = None
                ) -> Tuple[dict, dict]:
    """The deterministic multi-host workload: (arrays, statics) for one
    tick of a fleet whose demand (``n``) and existing-node usage
    (``ex_used0``) move every tick while the catalog stays put —
    exactly the DIRTY_FIELDS patch shape. With ``slab=(lo, hi, Np)``
    the slot-sharded tables come back as LocalSlab covering only
    global rows [lo, hi) of the PADDED slot range — the whole-arena
    arrays are never built on any single host. shape keys: G, T, n_max,
    E, P, Z, C, D, pods_per_group."""
    G, T = shape["G"], shape["T"]
    n_max, E, P = shape["n_max"], shape["E"], shape["P"]
    Z, C, D = shape["Z"], shape["C"], shape["D"]
    ppg = shape["pods_per_group"]

    def g(field, rows, cols):
        return _gen(seed, tick, field, rows, cols)

    arrays = dict(
        A=(1 + g("A", T, D) % np.uint64(1 << 20)).astype(np.int64),
        avail_zc=(g("avail_zc", T, Z * C) % np.uint64(100)) <
        np.uint64(80),
        R=(1 + g("R", G, D) % np.uint64(1 << 8)).astype(np.int64),
        n=(np.uint64(ppg) + g("n", G, 1)[:, 0] %
           np.uint64(5)).astype(np.int64),
        F=(g("F", G, T) % np.uint64(100)) < np.uint64(70),
        agz=np.ones((G, Z), bool), agc=np.ones((G, C), bool),
        admit=np.ones((G, P), bool),
        daemon=np.zeros((G, P, D), np.int64),
        pool_types=np.ones((P, T), bool),
        pool_agz=np.ones((P, Z), bool),
        pool_agc=np.ones((P, C), bool),
        pool_limit=np.full((P, D), -1, np.int64),
        pool_used0=np.zeros((P, D), np.int64),
    )

    def slot_rows(field, lo, hi, cols, vmax, dtype):
        """Rows [lo, hi) of a slot table: true rows [0, E) carry data,
        rows past E are the inert padding _pad_slots would add."""
        out = np.zeros((hi - lo, cols), dtype)
        top = min(hi, E)
        if top > lo:
            vals = _gen(seed, tick, field, top - lo, cols, lo=lo)
            out[:top - lo] = (vals % np.uint64(vmax)).astype(dtype)
        return out

    if slab is None:
        arrays["ex_alloc"] = 1 + slot_rows("ex_alloc", 0, E, D,
                                           1 << 10, np.int64)
        arrays["ex_used0"] = slot_rows("ex_used0", 0, E, D, 4, np.int64)
        # slot-major grid (transposed into [G, E]) so a column slab of
        # the full table equals the slab-mode generation bit-for-bit
        arrays["ex_compat"] = (
            (_gen(seed, tick, "ex_compat", E, G) %
             np.uint64(100) < np.uint64(60)).T) if E else \
            np.zeros((G, 0), bool)
    else:
        lo, hi, Np = slab
        alloc = slot_rows("ex_alloc", lo, hi, D, 1 << 10, np.int64)
        alloc[:max(0, min(hi, E) - lo)] += 1
        arrays["ex_alloc"] = LocalSlab(alloc, lo, hi, 0, (Np, D))
        arrays["ex_used0"] = LocalSlab(
            slot_rows("ex_used0", lo, hi, D, 4, np.int64),
            lo, hi, 0, (Np, D))
        compat = np.zeros((G, hi - lo), bool)
        top = min(hi, E)
        if top > lo:
            # ex_compat is [G, slots]: hash on the slot-major grid so
            # column lo..hi of the full table equals this slab
            grid = _gen(seed, tick, "ex_compat", top - lo, G, lo=lo)
            compat[:, :top - lo] = (grid % np.uint64(100) <
                                    np.uint64(60)).T
        arrays["ex_compat"] = LocalSlab(compat, lo, hi, 1, (G, Np))
    return arrays, dict(n_max=n_max, E=E, P=P)


def oracle_out(arrays: dict, *, n_max: int, E: int, P: int) -> dict:
    """The single-process CPU oracle: the SAME shared dispatch the
    local solver uses (parallel/mesh.dispatch_mesh) pinned to one
    device — the fingerprint baseline every distributed solve must
    match bit-for-bit."""
    from .mesh import dispatch_mesh
    return dispatch_mesh(arrays, n_max=n_max, E=E, P=P, V=0, ndev=1,
                         cache={})


def result_fingerprint(out: dict) -> str:
    """sha256 over every output tensor's name/dtype/shape/bytes — the
    cross-process and cross-arm decision-identity check."""
    h = hashlib.sha256()
    for k in sorted(out):
        a = np.ascontiguousarray(np.asarray(out[k]))
        h.update(f"{k}:{a.dtype}:{a.shape}:".encode())
        h.update(a.tobytes())
    return h.hexdigest()


# -- the distributed dispatch ----------------------------------------------

def dispatch_dist(arrays: dict, *, n_max: int, E: int, P: int,
                  mesh: Mesh, cache: dict, dirty=None,
                  metrics=None) -> dict:
    """dispatch_mesh's distributed twin: always the 2-D dp x tp kernel
    (a distributed mesh exists precisely because the slot axis outgrew
    one process), inputs committed per-process via commit_global, the
    sharded arena RESIDENT across ticks with the same dirty-list patch
    contract as _place_resident, outputs assembled with
    process_allgather. Slot-sharded fields may arrive as LocalSlab (the
    no-full-arena path); everything else is host-replicated numpy.
    Requires K == 0 (minValues floors stay on the 1-D type mesh)."""
    from jax.experimental import multihost_utils as mhu

    if arrays.get("mv_floor") is not None:
        raise ValueError("distributed mesh solve does not take "
                         "minValues floors")
    ndp = mesh.shape[AXIS_DP]
    ntp = mesh.shape[AXIS]
    N = E + n_max
    Np = ((N + ndp - 1) // ndp) * ndp
    specs = _input_specs2()
    fields = [f for f in KernelInputs._fields
              if arrays.get(f) is not None]
    T = int(np.asarray(
        arrays["A"].array if isinstance(arrays["A"], LocalSlab)
        else arrays["A"]).shape[0])
    Tp = ((T + ntp - 1) // ntp) * ntp

    def shape_of(v):
        return tuple(v.global_shape) if isinstance(v, LocalSlab) \
            else tuple(np.asarray(v).shape)

    def commit(f):
        v = arrays[f]
        if isinstance(v, LocalSlab):
            return commit_global(v, mesh, getattr(specs, f))
        return commit_global(_prep_field(f, v, Tp, Np), mesh,
                             getattr(specs, f))

    key = ("dist2", n_max, E, P, ndp, ntp, Tp, Np,
           tuple((f, shape_of(arrays[f])) for f in fields))
    res = cache.get("resident")
    t0 = time.perf_counter()
    if dirty is not None and res is not None and res["key"] == key:
        mode = "patch" if dirty else "reuse"
        dev = res["dev"]
        placed = [f for f in dirty if f in fields]
        for f in placed:
            dev[f] = commit(f)
    else:
        mode = "full"
        dev = {f: commit(f) for f in fields}
        cache["resident"] = {"key": key, "dev": dev}
        cache["resident_gen"] = cache.get("resident_gen", 0) + 1
        placed = list(fields)
    commit_s = time.perf_counter() - t0
    cache["last_placement"] = {"mode": mode, "kernel": "dist2",
                               "fields": list(placed)}
    if metrics is not None:
        metrics.set_gauge("karpenter_solver_distmesh_processes",
                          jax.process_count())
        metrics.inc("karpenter_solver_distmesh_patch_total",
                    labels={"mode": mode})

    inp = KernelInputs(**dev)
    t0 = time.perf_counter()
    takes, leftover, carry = _solve_sharded2(
        inp, n_max, E, P, mesh, sum_only=_resolve_sum_only(mesh))
    jax.block_until_ready(takes)
    solve_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    if jax.process_count() == 1:
        takes = np.asarray(takes)
        leftover = np.asarray(leftover)
        carry = carry._replace(**{f: np.asarray(getattr(carry, f))
                                  for f in carry._fields})
    else:
        # ONE resharding program gathers every output: collective order
        # is fixed inside a single executable on every process, where a
        # launch-per-output gather leaves N small programs whose gloo
        # ops can interleave across the tick boundary (observed as a
        # preamble-size enforce failure on the recycled slot)
        repl = NamedSharding(mesh, PS())
        gathered = jax.jit(lambda xs: xs, out_shardings=repl)(
            (takes, leftover) + tuple(carry))
        host = [np.asarray(x.addressable_data(0)) for x in gathered]
        takes, leftover = host[0], host[1]
        carry = type(carry)(*host[2:])
        # tick barrier: gloo TCP is FIFO per pair, so once every
        # process has seen every other's barrier message, no collective
        # bytes from THIS tick are still in flight to collide with the
        # next tick's receive slots
        seq = cache["tick_seq"] = cache.get("tick_seq", 0) + 1
        mhu.sync_global_devices(f"distmesh:tick:{seq}")
    gather_s = time.perf_counter() - t0
    cache["last_timing"] = {"commit_s": commit_s, "solve_s": solve_s,
                            "gather_s": gather_s}
    return _out_dict(takes, leftover, carry, T, N=N)


# -- worker control plane --------------------------------------------------

def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return buf


def _pack_arrays(arrays: Dict[str, np.ndarray]) -> bytes:
    bio = io.BytesIO()
    np.savez(bio, **{k: np.asarray(v) for k, v in arrays.items()})
    return bio.getvalue()


def _unpack_arrays(payload: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(payload), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def _send_msg(sock: socket.socket, msg: dict,
              arrays: Optional[Dict[str, np.ndarray]] = None) -> None:
    """One frame: !II (header len, payload len) + JSON header + npz
    payload. Loopback wire — framing over compression."""
    payload = _pack_arrays(arrays) if arrays else b""
    head = json.dumps(msg).encode()
    sock.sendall(struct.pack("!II", len(head), len(payload)))
    sock.sendall(head)
    if payload:
        sock.sendall(payload)


def _recv_msg(sock: socket.socket
              ) -> Tuple[Optional[dict], Dict[str, np.ndarray]]:
    """Inverse of _send_msg; (None, {}) on orderly close."""
    try:
        raw = _recv_exact(sock, 8)
    except ConnectionError:
        return None, {}
    hl, pl = struct.unpack("!II", raw)
    msg = json.loads(_recv_exact(sock, hl).decode())
    arrays = _unpack_arrays(_recv_exact(sock, pl)) if pl else {}
    return msg, arrays


def _slabs_from_frame(msg: dict, arrays: Dict[str, np.ndarray]) -> dict:
    """Rebuild LocalSlab fields a solve_frame message shipped: the
    header carries {field: [lo, hi, axis, global_shape]}."""
    out = dict(arrays)
    for f, (lo, hi, axis, gshape) in (msg.get("slabs") or {}).items():
        out[f] = LocalSlab(arrays[f], int(lo), int(hi), int(axis),
                           tuple(int(s) for s in gshape))
    return out


def run_worker(control: str, proc_id: int) -> None:
    """One mesh-group process: connect to the coordinator's control
    socket, then serve commands until halt/close. jax.distributed work
    only starts at the 'mesh' command, so the same loop also serves the
    single-process oracle role. Exits via os._exit — after a peer dies
    the distributed runtime's destructors can hang in collectives, and
    the coordinator owns lifecycle anyway."""
    host, _, port = control.rpartition(":")
    sock = socket.create_connection((host, int(port)))
    _send_msg(sock, {"hello": proc_id})
    mesh: Optional[Mesh] = None
    cache: dict = {}
    batch_cache: dict = {}
    code = 0
    while True:
        msg, arrays = _recv_msg(sock)
        if msg is None or msg.get("cmd") == "halt":
            break
        try:
            reply, rarrays = _worker_cmd(msg, arrays, proc_id, cache,
                                         batch_cache)
            if reply.get("_mesh_built"):
                mesh = reply.pop("_mesh_built")
                cache["mesh"] = mesh
            # epoch fencing: every reply echoes the REQUEST's mesh
            # epoch, so the coordinator can reject late bytes from a
            # prior group formation (fleet/meshgroup.py _broadcast)
            if "epoch" in msg:
                reply.setdefault("epoch", msg["epoch"])
            _send_msg(sock, reply, rarrays)
        except Exception as e:  # report, don't die: coordinator decides
            log.exception("worker %d: command %r failed", proc_id,
                          msg.get("cmd"))
            try:
                err = {"ok": False, "error": repr(e)}
                if "epoch" in msg:
                    err["epoch"] = msg["epoch"]
                _send_msg(sock, err)
            except Exception:
                code = 1
                break
    os._exit(code)


def _worker_cmd(msg: dict, arrays: Dict[str, np.ndarray], proc_id: int,
                cache: dict, batch_cache: dict
                ) -> Tuple[dict, Optional[Dict[str, np.ndarray]]]:
    cmd = msg["cmd"]
    if cmd == "mesh":
        cfg = DistConfig(msg["coordinator"], int(msg["num_processes"]),
                         int(msg["process_id"]),
                         msg.get("local_devices"))
        init_process(cfg)
        mesh = dist_mesh2()
        return {"ok": True, "_mesh_built": mesh,
                "ndev": int(mesh.devices.size),
                "dp": int(mesh.shape[AXIS_DP]),
                "tp": int(mesh.shape[AXIS]),
                "process_index": int(jax.process_index())}, None

    if cmd in ("solve_seeded", "solve_frame"):
        mesh = cache.get("mesh")
        if mesh is None:
            raise RuntimeError("mesh not initialized (send 'mesh' first)")
        if cmd == "solve_seeded":
            shape = msg["shape"]
            Np, lo, hi = slab_rows(shape["n_max"], shape["E"], mesh)
            inp, statics = tick_arrays(shape, int(msg["seed"]),
                                       int(msg["tick"]), slab=(lo, hi, Np))
        else:
            inp = _slabs_from_frame(msg, arrays)
            statics = {k: int(msg[k]) for k in ("n_max", "E", "P")}
        t0 = time.perf_counter()
        out = dispatch_dist(inp, mesh=mesh, cache=cache,
                            dirty=msg.get("dirty"), **statics)
        wall = time.perf_counter() - t0
        reply = {"ok": True, "fingerprint": result_fingerprint(out),
                 "wall_s": wall,
                 "mode": cache["last_placement"]["mode"],
                 "timing": cache.get("last_timing", {})}
        want = bool(msg.get("want_arrays")) and proc_id == 0
        return reply, (out if want else None)

    if cmd == "solve_batch":
        # routed SolveBatch lanes: independent vmapped solves over THIS
        # process's local devices — no collectives, so no global mesh
        from ..ops.ffd_jax import solve_scan_packed1_many
        from .mesh import shard_batch
        kv = {k: int(v) for k, v in msg["kv"].items()}
        stack = arrays["stack"]
        ndev = len(jax.local_devices())
        d_stack, B = shard_batch(stack, ndev, batch_cache)
        out = np.asarray(solve_scan_packed1_many(d_stack, **kv))[:B]
        return {"ok": True, "lanes": int(B)}, {"out": out}

    if cmd == "solve_oracle":
        shape = msg["shape"]
        inp, statics = tick_arrays(shape, int(msg["seed"]),
                                   int(msg["tick"]))
        out = oracle_out(inp, **statics)
        reply = {"ok": True, "fingerprint": result_fingerprint(out)}
        return reply, (out if msg.get("want_arrays") else None)

    if cmd == "canary":
        # canary-gated re-admission (fleet/meshgroup.py): solve the
        # tiny seeded workload into a THROWAWAY cache — proving the
        # freshly formed mesh still solves correctly must not disturb
        # production residency or its patch contract
        mesh = cache.get("mesh")
        if mesh is None:
            raise RuntimeError("mesh not initialized (send 'mesh' first)")
        shape = msg["shape"]
        Np, lo, hi = slab_rows(shape["n_max"], shape["E"], mesh)
        inp, statics = tick_arrays(shape, int(msg["seed"]),
                                   int(msg["tick"]), slab=(lo, hi, Np))
        out = dispatch_dist(inp, mesh=mesh, cache={}, **statics)
        return {"ok": True,
                "fingerprint": result_fingerprint(out)}, None

    if cmd == "sleep":
        # chaos-harness wedge injection (tests/test_selfheal.py): hold
        # the reply hostage for a bounded window so the coordinator's
        # per-reply watchdog can be exercised without a real stuck
        # collective
        time.sleep(float(msg["s"]))
        return {"ok": True}, None

    raise ValueError(f"unknown command {cmd!r}")


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(
        description="distmesh worker process (fleet/meshgroup.py "
                    "spawns these; not a user-facing CLI)")
    ap.add_argument("--worker", action="store_true", required=True)
    ap.add_argument("--control", required=True,
                    help="host:port of the coordinator control socket")
    ap.add_argument("--proc-id", type=int, default=None,
                    help="process id; defaults to the POD_NAME "
                         "StatefulSet ordinal + 1 (chart contract), "
                         "else 0")
    args = ap.parse_args(argv)
    pid = args.proc_id
    if pid is None:
        tail = os.environ.get("POD_NAME", "").rsplit("-", 1)[-1]
        pid = int(tail) + 1 if tail.isdigit() else 0
    run_worker(args.control, pid)


if __name__ == "__main__":
    main()
