from .mesh import solve_mesh, solve_scan_sharded  # noqa: F401
