from .mesh import (  # noqa: F401
    dispatch_mesh,
    shard_batch,
    shard_lanes,
    solve_mesh,
    solve_mesh2,
    solve_scan_sharded,
    solve_scan_sharded2,
)
