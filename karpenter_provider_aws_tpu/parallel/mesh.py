"""Multi-device solve: the mesh as the default execution substrate.

The scaling-book recipe applied to this workload: pick a mesh, annotate
shardings, let XLA insert collectives. Three sharded execution shapes:

1. **Type-parallel (1-D ``("tp",)`` mesh)** — the solve's wide axis is
   the instance-type catalog (~850 types at full EC2 scale); the
   sequential FFD carry is a few KB. ``A[T,D]``, ``avail_zc[T,ZC]``,
   ``F[G,T]``, ``pool_types[P,T]`` and the per-node candidate masks
   ``types[N,T]`` shard over ``tp``; the scan carry, group tensors and
   existing-node state stay replicated; two ``pmax`` reductions per scan
   step ride ICI.

2. **2-D pods x types (``("dp","tp")`` mesh)** — for one giant solve the
   node-slot state (``used[N,D]``, ``types[N,T]``; N grows with the pod
   count) additionally shards over ``dp`` (ops/ffd_jax._solve_dp): slot
   tables split by global slot id, prefix sums become local-cumsum +
   all_gathered shard totals, pool/pod accounting becomes ``psum``. This
   lifts the one-solve ceiling from ~50k to 500k pods. Per scan step:
   (1 + P) tp-pmax reductions, (P + 1) dp all_gathers (P pool-budget
   prefixes + the greedy-fill prefix, each gathering ndp scalars) and 2
   dp psums — all O(ndp) bytes, latency-dominated. minValues floors
   (K > 0) fall back to shape 1, whose floor segment-max already shards
   exactly over types.

3. **Batch data-parallel (``shard_batch``)** — stacked ``[B, W]`` packed
   arenas from SolveBatch frames / coalesced riders commit with
   ``NamedSharding(P("dp", None))`` so the jit-of-vmap packed kernel
   lands B/ndev independent lanes per chip with ZERO cross-device
   collectives.

Decisions are identical to the single-device kernel by construction in
every shape: the pmax of per-shard maxima IS the global max, distributed
prefixes reproduce the global slot order exactly, batch lanes are
independent, and everything downstream of the reductions is replicated
arithmetic.

``dispatch_mesh`` additionally keeps a RESIDENT sharded arena per cache:
on rows-tier delta ticks only the dirty fields are re-prepped and
``device_put`` with their owning sharding; clean fields stay on-device
(never a full re-distribute).

Multi-chip hardware isn't reachable from this environment; tests validate
on an 8-virtual-device CPU mesh (tests/conftest.py) and the driver
dry-runs ``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from ..ops.ffd_jax import Carry, KernelInputs, _solve, _solve_dp

AXIS = "tp"
AXIS_DP = "dp"

#: mesh fingerprint -> detected sum_only verdict (solve_scan_sharded
#: memoization). Keyed by a STABLE mesh identity — platform, platform
#: version and device ids — never by id(mesh): a garbage-collected mesh's
#: recycled id() could otherwise serve a stale verdict to a different
#: backend (e.g. a CPU test mesh inheriting a TPU mesh's sum_only=True).
_SUM_ONLY_CACHE: dict = {}


def _mesh_key(mesh: Mesh) -> tuple:
    """Stable sum_only cache key: everything _needs_sum_only sniffs."""
    try:
        devs = tuple(sorted(d.id for d in mesh.devices.flat))
        dev0 = mesh.devices.flat[0]
        ver = getattr(dev0.client, "platform_version", "") or ""
        return (dev0.platform, ver, devs)
    except Exception:
        return ("unknown", "", ())


def _needs_sum_only(mesh: Mesh) -> bool:
    """True when the mesh's cross-shard maxima should ride the
    all_gather emulation instead of native pmax (ops/ffd_jax._axis_max,
    exact either way). The tunneled axon AOT compiler rejects int64 pmax
    ("Supported lowering only of Sum all reduce") while AllGather lowers
    fine; since the gathered buffers are KB-scale and latency-dominated,
    the emulation costs nothing measurable, so ANY tpu-platform mesh
    defaults to it — a version-string sniff alone would silently miss an
    axon plugin whose platform_version is a bare version number.
    Overridable via KARP_SUM_ONLY_COLLECTIVES (KARP_ is the repo's
    env-var prefix — see KARP_JAX_PLATFORMS; strconv.ParseBool
    semantics, typos are errors not False)."""
    import logging
    import os

    from ..options import _parse_bool
    log = logging.getLogger(__name__)
    env = os.environ.get("KARP_SUM_ONLY_COLLECTIVES")
    if env is not None:
        val = _parse_bool(env)
        log.info("mesh collectives: sum_only=%s (KARP_SUM_ONLY_"
                 "COLLECTIVES override)", val)
        return val
    try:
        dev = mesh.devices.flat[0]
        ver = getattr(dev.client, "platform_version", "") or ""
        val = dev.platform == "tpu" or "axon" in ver.lower()
    except Exception:
        val = False
    if val:
        log.info("mesh collectives: sum_only=True (tpu/axon backend — "
                 "int64 pmax may not lower; using all_gather max)")
    return val


def _resolve_sum_only(mesh: Mesh) -> bool:
    """Memoized _needs_sum_only: detection is a property of the mesh's
    backend, so a steady-state control loop doesn't re-sniff and re-log
    once per solve (stable key — see _SUM_ONLY_CACHE)."""
    key = _mesh_key(mesh)
    cached = _SUM_ONLY_CACHE.get(key)
    if cached is None:
        cached = _needs_sum_only(mesh)
        _SUM_ONLY_CACHE[key] = cached
    return cached


def _pick_devices(n_devices: Optional[int] = None,
                  force_host: bool = False):
    """The one device-selection helper (shared with __graft_entry__ —
    previously a diverged duplicate).

    Default mode returns the default backend's LOCAL devices truncated
    to ``n_devices``, falling back to host CPU devices when the backend
    has fewer than requested. Local, not global: under a
    ``jax.distributed`` job ``jax.devices()`` spans every process, and
    every caller of this helper builds a single-process mesh — the
    distributed tier (parallel/distmesh.py) owns its own process-major
    global ordering.

    ``force_host=True`` is the dryrun/driver discipline: select
    ``n_devices`` VIRTUAL host devices without touching any accelerator
    backend — the device-count flag and the platform pin must both land
    before the first backend init (they are read once, at client
    creation), and initializing a default (TPU/tunnel) backend can
    block for minutes or die on a broken runtime."""
    if force_host:
        import os

        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count"
                f"={n_devices}").strip()
        jax.config.update("jax_platforms", "cpu")  # beats site hooks
        avail = jax.local_devices(backend="cpu")
        assert len(avail) >= n_devices, \
            f"need {n_devices} devices, have {len(avail)}"
        return avail[:n_devices]
    devices = jax.local_devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            devices = jax.local_devices(backend="cpu")
        devices = devices[:n_devices]
    return devices


def solve_mesh(n_devices: Optional[int] = None,
               devices=None) -> Mesh:
    """A 1-D mesh over the type-parallel axis."""
    if devices is None:
        devices = _pick_devices(n_devices)
    return Mesh(np.asarray(devices), axis_names=(AXIS,))


def _default_dp(ndev: int) -> int:
    """dp-axis extent for a 2-D mesh over ``ndev`` devices. KARP_MESH_DP
    overrides (must divide the device count); default is the largest
    power-of-two divisor with dp <= tp — the type catalog is the
    reliably-wide axis, so it keeps the wider split. 8 devices -> 2x4;
    2 devices -> 1x2 (degenerates to the pure type mesh)."""
    import logging
    import os

    env = os.environ.get("KARP_MESH_DP")
    if env:
        try:
            v = int(env)
        except ValueError:
            v = 0
        if v >= 1 and ndev % v == 0:
            return v
        logging.getLogger(__name__).warning(
            "KARP_MESH_DP=%r invalid for %d devices; using default",
            env, ndev)
    dp = 1
    while ndev % (dp * 2) == 0 and (dp * 2) ** 2 <= ndev:
        dp *= 2
    return dp


DP2_MIN_SLOTS = 2048


def _dp2_min_slots() -> int:
    """Slot-count floor below which dispatch_mesh keeps the 1-D type
    mesh even when a dp factor is available. The 2-D kernel exists to
    split a slot-indexed carry too big to replicate (the 500k-pod
    envelope, slot axes in the thousands); under ~2k slots its extra
    per-step collectives and its much larger compiled program are pure
    overhead. KARP_MESH_DP2_MIN_SLOTS overrides; 0 forces dp2 on, and
    negatives clamp to 0 (every real slot count beats a negative floor,
    so they mean "force on" too — not a crash, not a silent default)."""
    import os

    env = os.environ.get("KARP_MESH_DP2_MIN_SLOTS")
    if env:
        try:
            return max(0, int(env))
        except ValueError:
            pass
    return DP2_MIN_SLOTS


def solve_mesh2(n_devices: Optional[int] = None, devices=None,
                dp: Optional[int] = None) -> Mesh:
    """A 2-D ``("dp","tp")`` mesh: node-slot (pods) axis x type axis."""
    if devices is None:
        devices = _pick_devices(n_devices)
    ndev = len(devices)
    ndp = dp if dp is not None else _default_dp(ndev)
    if ndp < 1 or ndev % ndp:
        raise ValueError(f"dp={ndp} does not divide {ndev} devices")
    return Mesh(np.asarray(devices).reshape(ndp, ndev // ndp),
                axis_names=(AXIS_DP, AXIS))


def _pad_types(inp: KernelInputs, n_shards: int) -> Tuple[KernelInputs, int]:
    """Pad the type axis to a multiple of the shard count (host-side
    numpy — runs before any device placement). Padded types have zero
    allocatable and no offerings -> never candidates."""
    T = inp.A.shape[0]
    Tp = ((T + n_shards - 1) // n_shards) * n_shards
    if Tp == T:
        return inp, T
    pad = Tp - T

    def padT0(a):  # type axis first
        a = np.asarray(a)
        return np.concatenate(
            [a, np.zeros((pad,) + a.shape[1:], a.dtype)], axis=0)

    def padT1(a):  # type axis second
        a = np.asarray(a)
        return np.concatenate(
            [a, np.zeros(a.shape[:1] + (pad,) + a.shape[2:], a.dtype)],
            axis=1)

    return inp._replace(A=padT0(inp.A), avail_zc=padT0(inp.avail_zc),
                        F=padT1(inp.F), pool_types=padT1(inp.pool_types)), T


def _input_specs(has_mv: bool) -> KernelInputs:
    """Partition specs per kernel input: type-axis sharded tensors vs the
    replicated carry-adjacent state (module docstring)."""
    repl = PS()
    return KernelInputs(
        A=PS(AXIS, None), avail_zc=PS(AXIS, None),
        R=repl, n=repl, F=PS(None, AXIS), agz=repl, agc=repl,
        admit=repl, daemon=repl,
        pool_types=PS(None, AXIS), pool_agz=repl, pool_agc=repl,
        pool_limit=repl, pool_used0=repl,
        ex_alloc=repl, ex_used0=repl, ex_compat=repl,
        # pair type indices are global; the kernel localizes per shard
        mv_floor=repl if has_mv else None,
        mv_pairs_t=repl if has_mv else None,
        mv_pairs_v=repl if has_mv else None)


def _input_specs2() -> KernelInputs:
    """Partition specs for the 2-D kernel: types over ``tp``, the slot-
    indexed existing tables over ``dp``, the rest replicated. minValues
    arrays are absent by construction (callers gate K == 0)."""
    repl = PS()
    return KernelInputs(
        A=PS(AXIS, None), avail_zc=PS(AXIS, None),
        R=repl, n=repl, F=PS(None, AXIS), agz=repl, agc=repl,
        admit=repl, daemon=repl,
        pool_types=PS(None, AXIS), pool_agz=repl, pool_agc=repl,
        pool_limit=repl, pool_used0=repl,
        ex_alloc=PS(AXIS_DP, None), ex_used0=PS(AXIS_DP, None),
        ex_compat=PS(None, AXIS_DP),
        mv_floor=None, mv_pairs_t=None, mv_pairs_v=None)


def _shard_map():
    """The shard_map entry point across jax versions, replication checker
    disabled (it can't see through pmax-into-replicated arithmetic; the
    kwarg name varies by version)."""
    try:
        from jax import shard_map as _smap

        def wrap(f, mesh, in_specs, out_specs):
            try:
                return _smap(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
            except TypeError:
                return _smap(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map as _esmap

        def wrap(f, mesh, in_specs, out_specs):
            return _esmap(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)
    return wrap


@partial(jax.jit,
         static_argnames=("n_max", "E", "P", "V", "mesh", "sum_only"))
def _solve_sharded(inp: KernelInputs, n_max: int, E: int, P: int,
                   mesh: Mesh, V: int = 0, sum_only: bool = False):
    shard_map = _shard_map()
    repl = PS()
    in_specs = _input_specs(inp.mv_floor is not None)
    out_specs = (repl, repl, Carry(
        used=repl, types=PS(None, AXIS), zones=repl, ct=repl,
        pool=repl, alive=repl, num_nodes=repl, pool_used=repl))
    fn = shard_map(partial(_solve, n_max=n_max, E=E, P=P, axis=AXIS, V=V,
                           sum_only=sum_only),
                   mesh=mesh, in_specs=(in_specs,), out_specs=out_specs)
    return fn(inp)


@partial(jax.jit, static_argnames=("n_max", "E", "P", "mesh", "sum_only"))
def _solve_sharded2(inp: KernelInputs, n_max: int, E: int, P: int,
                    mesh: Mesh, sum_only: bool = False):
    shard_map = _shard_map()
    repl = PS()
    in_specs = _input_specs2()
    out_specs = (PS(None, AXIS_DP), repl, Carry(
        used=PS(AXIS_DP, None), types=PS(AXIS_DP, AXIS),
        zones=PS(AXIS_DP, None), ct=PS(AXIS_DP, None),
        pool=PS(AXIS_DP), alive=PS(AXIS_DP), num_nodes=repl,
        pool_used=repl))
    fn = shard_map(partial(_solve_dp, n_max=n_max, E=E, P=P,
                           dp_axis=AXIS_DP, tp_axis=AXIS,
                           sum_only=sum_only),
                   mesh=mesh, in_specs=(in_specs,), out_specs=out_specs)
    return fn(inp)


def _pad_slots(inp: KernelInputs, E: int, n_max: int, ndp: int
               ) -> Tuple[KernelInputs, int]:
    """Pad the slot axis of the existing-node tables to the full padded
    slot range Np = ceil(N/ndp)*ndp (host-side numpy). The dp kernel
    indexes these tables by slot row, so they must span every slot; rows
    beyond E are inert (zero allocatable, compat False) and the kernel's
    free_slots math uses the TRUE N, so padded slots never open."""
    N = E + n_max
    Np = ((N + ndp - 1) // ndp) * ndp

    def grow0(a):
        a = np.asarray(a)
        out = np.zeros((Np,) + a.shape[1:], a.dtype)
        out[:a.shape[0]] = a
        return out

    ex_compat = np.asarray(inp.ex_compat)
    grown = np.zeros(ex_compat.shape[:1] + (Np,), np.bool_)
    grown[:, :ex_compat.shape[1]] = ex_compat
    return inp._replace(ex_alloc=grow0(inp.ex_alloc),
                        ex_used0=grow0(inp.ex_used0),
                        ex_compat=grown), N


def solve_scan_sharded2(inp: KernelInputs, n_max: int, E: int, P: int,
                        mesh: Mesh, sum_only: Optional[bool] = None
                        ) -> Tuple[jax.Array, jax.Array, Carry]:
    """2-D pods x types solve over ``mesh``; same (takes, leftover,
    carry) contract as ops.ffd_jax.solve_scan, decisions identical.
    Requires K == 0 (no minValues floors — use solve_scan_sharded)."""
    if inp.mv_floor is not None:
        raise ValueError("2-D mesh solve does not take minValues floors; "
                         "use the 1-D type mesh (solve_scan_sharded)")
    if sum_only is None:
        sum_only = _resolve_sum_only(mesh)
    ndp = mesh.shape[AXIS_DP]
    ntp = mesh.shape[AXIS]
    padded, T = _pad_types(inp, ntp)
    padded, N = _pad_slots(padded, E, n_max, ndp)
    specs = _input_specs2()
    padded = KernelInputs(*[
        None if x is None
        else jax.device_put(np.asarray(x), NamedSharding(mesh, s))
        for x, s in zip(padded, specs)])
    takes, leftover, carry = _solve_sharded2(padded, n_max, E, P, mesh,
                                             sum_only=sum_only)
    takes = takes[:, :N]
    carry = carry._replace(
        used=carry.used[:N], types=carry.types[:N, :T],
        zones=carry.zones[:N], ct=carry.ct[:N],
        pool=carry.pool[:N], alive=carry.alive[:N])
    return takes, leftover, carry


def _batch_mesh(ndev: int, cache: dict) -> Mesh:
    """The cached 1-D batch-dp mesh for shard_batch/shard_lanes. Keyed
    on the DEVICE IDS, not just the count: a changed device set at the
    same count (backend re-init, a distmesh degrade swapping which local
    devices back the solver) must rebuild — a count-only key silently
    reuses a mesh over devices that may no longer exist."""
    ids = tuple(d.id for d in _pick_devices(ndev))
    mesh = cache.get("batch_mesh")
    if mesh is None or cache.get("batch_mesh_ids") != ids:
        mesh = cache["batch_mesh"] = Mesh(
            np.asarray(_pick_devices(ndev)), axis_names=(AXIS_DP,))
        cache["batch_mesh_ids"] = ids
    return mesh


def _shard_stacks(stacks: Dict[str, np.ndarray], ndev: int, cache: dict
                  ) -> Tuple[Dict[str, jax.Array], int]:
    """The one pad-to-device-multiple + device_put loop behind
    shard_batch and shard_lanes (previously duplicated): pad the shared
    leading batch axis B up to a device multiple by repeating each
    stack's last row (lanes of the vmapped kernels are independent, so
    pad lanes are inert — callers slice results [:B]) and commit every
    stack dp-sharded on the leading axis with trailing axes replicated.
    Returns (device dict, B)."""
    mesh = _batch_mesh(ndev, cache)
    first = np.asarray(next(iter(stacks.values())))
    B = first.shape[0]
    Bp = ((B + ndev - 1) // ndev) * ndev
    out = {}
    for k, a in stacks.items():
        a = np.asarray(a)
        if Bp != B:
            a = np.concatenate(
                [a, np.repeat(a[-1:], Bp - B, axis=0)], axis=0)
        spec = PS(AXIS_DP, *([None] * (a.ndim - 1)))
        out[k] = jax.device_put(a, NamedSharding(mesh, spec))
    return out, B


def shard_batch(stack: np.ndarray, ndev: int, cache: dict
                ) -> Tuple[jax.Array, int]:
    """Distribute a stacked [B, W] packed-solve batch across devices
    with NamedSharding(P("dp", None)) so the jit partitions the batch
    with zero cross-device collectives. Returns (device stack [Bp, W],
    B). Padding/commit semantics: _shard_stacks."""
    out, B = _shard_stacks({"stack": stack}, ndev, cache)
    return out["stack"], B


def shard_lanes(stacks: Dict[str, np.ndarray], ndev: int, cache: dict
                ) -> Tuple[Dict[str, jax.Array], int]:
    """shard_batch for a DICT of per-lane stacks sharing a leading batch
    axis (the consolidation subset search: gid/n/dead/keep/price lanes).
    The shared union-arena tensors stay host-side and replicate at trace
    time. Padding/commit semantics: _shard_stacks."""
    return _shard_stacks(stacks, ndev, cache)


def _prep_field(name: str, a, Tp: int, Np: Optional[int]) -> np.ndarray:
    """Host-side per-field prep for mesh placement: pad the type axis to
    the tp-shard multiple (inert types) and, for the 2-D kernel (Np set),
    the slot axis of the existing tables to Np (inert slots).
    Deterministic given the shape statics, so a dirty field of a resident
    arena can be re-prepped and re-placed alone."""
    a = np.asarray(a)

    def grow(arr, ax, size):
        if arr.shape[ax] == size:
            return arr
        shape = list(arr.shape)
        shape[ax] = size - arr.shape[ax]
        return np.concatenate([arr, np.zeros(shape, arr.dtype)], axis=ax)

    if name in ("A", "avail_zc"):
        return grow(a, 0, Tp)
    if name in ("F", "pool_types"):
        return grow(a, 1, Tp)
    if Np is not None:
        if name in ("ex_alloc", "ex_used0"):
            return grow(a, 0, Np)
        if name == "ex_compat":
            return grow(a, 1, Np)
    return a


def _place_resident(arrays: dict, mesh: Mesh, specs: KernelInputs,
                    kern: str, Tp: int, Np: Optional[int], statics_key,
                    cache: dict, dirty, metrics) -> KernelInputs:
    """Build the device-resident KernelInputs for a mesh dispatch.

    ``dirty=None`` means the caller makes no claim about the host arrays
    (stateless request, fresh prep, retry at a grown bucket): full
    placement. A list means the caller guarantees every field NOT listed
    is unchanged since the previous dispatch against this cache — only
    the listed fields are re-prepped and ``device_put`` with the owning
    sharding; everything else reuses the resident sharded buffers, so a
    rows-tier tick moves O(dirty) bytes host-to-device instead of the
    whole arena. The guarantee is only honored when the resident key
    (kernel, mesh, statics, field shapes) matches exactly."""
    fields = [f for f in KernelInputs._fields if arrays.get(f) is not None]
    key = (statics_key, Tp, Np,
           tuple((f, tuple(np.asarray(arrays[f]).shape)) for f in fields))
    res = cache.get("resident")
    if dirty is not None and res is not None and res["key"] == key:
        mode = "patch" if dirty else "reuse"
        dev = res["dev"]
        placed = [f for f in dirty if f in fields]
        for f in placed:
            dev[f] = jax.device_put(
                _prep_field(f, arrays[f], Tp, Np),
                NamedSharding(mesh, getattr(specs, f)))
    else:
        mode = "full"
        dev = {}
        for f in fields:
            dev[f] = jax.device_put(
                _prep_field(f, arrays[f], Tp, Np),
                NamedSharding(mesh, getattr(specs, f)))
        cache["resident"] = {"key": key, "dev": dev}
        # full placements are a structural edge for identity-keyed caches
        # derived from the resident arena (consolidation _base_tables):
        # the generation rides TPUSolver.arena_epoch() so a mesh re-place
        # invalidates exactly like a packed-buffer structural rebuild
        cache["resident_gen"] = cache.get("resident_gen", 0) + 1
        placed = list(fields)
    cache["last_placement"] = {"mode": mode, "kernel": kern,
                               "fields": list(placed)}
    if metrics is not None:
        metrics.inc("karpenter_solver_mesh_dispatch_total",
                    labels={"kernel": kern})
        metrics.inc("karpenter_solver_mesh_resident_total",
                    labels={"mode": mode})
    return KernelInputs(**dev)


def dispatch_mesh(arrays: dict, *, n_max: int, E: int, P: int, V: int,
                  ndev: int, cache: dict, dirty=None,
                  metrics=None) -> dict:
    """The one mesh-dispatch implementation shared by the local solver
    (TPUSolver._dispatch_mesh) and the sidecar server: build/reuse the
    mesh (cache key: device count), pick the kernel (2-D pods x types
    when the dp factor is > 1, the slot axis is big enough to be worth
    splitting — see _dp2_min_slots — and there are no minValues floors,
    else the 1-D type mesh), keep the sharded arena resident across
    ticks (see _place_resident), run the solve, and return the carry as
    the same dict shape as hostpack.unpack_outputs1 — so the two paths
    can never drift apart."""
    has_mv = arrays.get("mv_floor") is not None
    N = E + n_max
    ndp = 1 if (has_mv or N < _dp2_min_slots()) else _default_dp(ndev)
    if ndp > 1:
        kern = "dp2"
        mesh = cache.get("mesh2")
        if mesh is None or mesh.devices.size != ndev:
            mesh = cache["mesh2"] = solve_mesh2(ndev)
        ndp = mesh.shape[AXIS_DP]
        ntp = mesh.shape[AXIS]
        specs = _input_specs2()
        Np = ((N + ndp - 1) // ndp) * ndp
    else:
        kern = "tp"
        mesh = cache.get("mesh")
        if mesh is None or mesh.devices.size != ndev:
            mesh = cache["mesh"] = solve_mesh(ndev)
        ntp = ndev
        specs = _input_specs(has_mv)
        Np = None
    sum_only = _resolve_sum_only(mesh)
    T = int(np.asarray(arrays["A"]).shape[0])
    Tp = ((T + ntp - 1) // ntp) * ntp
    inp = _place_resident(arrays, mesh, specs, kern, Tp, Np,
                          (kern, _mesh_key(mesh), n_max, E, P, V),
                          cache, dirty, metrics)
    if kern == "dp2":
        takes, leftover, carry = _solve_sharded2(
            inp, n_max, E, P, mesh, sum_only=sum_only)
    else:
        takes, leftover, carry = _solve_sharded(
            inp, n_max, E, P, mesh, V=V, sum_only=sum_only)
    return _out_dict(takes, leftover, carry, T,
                     N=N if kern == "dp2" else None)


def _out_dict(takes, leftover, carry: Carry, T: int,
              N: Optional[int] = None) -> dict:
    """Assemble the solve outputs into the hostpack.unpack_outputs1 dict
    shape shared by every dispatch surface (local mesh, sidecar,
    distmesh, oracles) — one place strips the inert type padding and,
    when ``N`` is given (slot-sharded kernels), the inert slot padding,
    so the surfaces can never drift apart."""
    carry = Carry(*[np.asarray(x) for x in carry])
    takes = np.asarray(takes)
    if N is not None:
        takes = takes[:, :N]
        carry = carry._replace(
            used=carry.used[:N], types=carry.types[:N],
            zones=carry.zones[:N], ct=carry.ct[:N],
            pool=carry.pool[:N], alive=carry.alive[:N])
    return dict(
        takes=takes, leftover=np.asarray(leftover),
        num_nodes=np.asarray([carry.num_nodes]),
        used=carry.used, pool=carry.pool,
        pool_used=carry.pool_used,
        types=carry.types[:, :T], zones=carry.zones,
        ct=carry.ct, alive=carry.alive)


def solve_scan_sharded(inp: KernelInputs, n_max: int, E: int, P: int,
                       mesh: Mesh, V: int = 0,
                       sum_only: Optional[bool] = None
                       ) -> Tuple[jax.Array, jax.Array, Carry]:
    """Type-parallel solve over ``mesh``; same (takes, leftover, carry)
    contract as ops.ffd_jax.solve_scan, decisions identical."""
    if sum_only is None:
        sum_only = _resolve_sum_only(mesh)
    n_shards = mesh.devices.size
    padded, T = _pad_types(inp, n_shards)
    # explicit placement onto the mesh per spec — never the default device
    # (the default backend may be a different/broken platform)
    specs = _input_specs(padded.mv_floor is not None)
    padded = KernelInputs(*[
        None if x is None
        else jax.device_put(np.asarray(x), NamedSharding(mesh, s))
        for x, s in zip(padded, specs)])
    takes, leftover, carry = _solve_sharded(padded, n_max, E, P, mesh, V=V,
                                            sum_only=sum_only)
    if padded.A.shape[0] != T:
        carry = carry._replace(types=carry.types[:, :T])
    return takes, leftover, carry
