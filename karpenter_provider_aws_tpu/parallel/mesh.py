"""Multi-device solve: tensor parallelism over the instance-type axis.

The scaling-book recipe applied to this workload: pick a mesh, annotate
shardings, let XLA insert collectives. The solve's wide axis is the
instance-type catalog (~850 types at full EC2 scale); the sequential FFD
carry is a few KB. So the mesh split is:

- type-sharded: ``A[T,D]``, ``avail_zc[T,ZC]``, ``F[G,T]``,
  ``pool_types[P,T]`` and the per-node candidate masks ``types[N,T]``
- replicated: the scan carry (used/zones/ct/pool/alive/pool_used), all
  group tensors, existing-node state
- collectives: two ``pmax`` reductions per scan step (open-slot headroom,
  new-node capacity) riding ICI — the analog of the reference's
  "single-threaded hot loop" parallelized across a chip's neighbors

Decisions are identical to the single-device kernel by construction: the
pmax of per-shard maxima IS the global max, and everything downstream of
the reductions is replicated arithmetic.

Multi-chip hardware isn't reachable from this environment; tests validate
on an 8-virtual-device CPU mesh (tests/conftest.py) and the driver
dry-runs ``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from ..ops.ffd_jax import Carry, KernelInputs, _solve

AXIS = "tp"

#: mesh fingerprint -> detected sum_only verdict (solve_scan_sharded
#: memoization). Keyed by a STABLE mesh identity — platform, platform
#: version and device ids — never by id(mesh): a garbage-collected mesh's
#: recycled id() could otherwise serve a stale verdict to a different
#: backend (e.g. a CPU test mesh inheriting a TPU mesh's sum_only=True).
_SUM_ONLY_CACHE: dict = {}


def _mesh_key(mesh: Mesh) -> tuple:
    """Stable sum_only cache key: everything _needs_sum_only sniffs."""
    try:
        devs = tuple(sorted(d.id for d in mesh.devices.flat))
        dev0 = mesh.devices.flat[0]
        ver = getattr(dev0.client, "platform_version", "") or ""
        return (dev0.platform, ver, devs)
    except Exception:
        return ("unknown", "", ())


def _needs_sum_only(mesh: Mesh) -> bool:
    """True when the mesh's cross-shard maxima should ride the
    all_gather emulation instead of native pmax (ops/ffd_jax._axis_max,
    exact either way). The tunneled axon AOT compiler rejects int64 pmax
    ("Supported lowering only of Sum all reduce") while AllGather lowers
    fine; since the gathered buffers are KB-scale and latency-dominated,
    the emulation costs nothing measurable, so ANY tpu-platform mesh
    defaults to it — a version-string sniff alone would silently miss an
    axon plugin whose platform_version is a bare version number.
    Overridable via KARP_SUM_ONLY_COLLECTIVES (KARP_ is the repo's
    env-var prefix — see KARP_JAX_PLATFORMS; strconv.ParseBool
    semantics, typos are errors not False)."""
    import logging
    import os

    from ..options import _parse_bool
    log = logging.getLogger(__name__)
    env = os.environ.get("KARP_SUM_ONLY_COLLECTIVES")
    if env is not None:
        val = _parse_bool(env)
        log.info("mesh collectives: sum_only=%s (KARP_SUM_ONLY_"
                 "COLLECTIVES override)", val)
        return val
    try:
        dev = mesh.devices.flat[0]
        ver = getattr(dev.client, "platform_version", "") or ""
        val = dev.platform == "tpu" or "axon" in ver.lower()
    except Exception:
        val = False
    if val:
        log.info("mesh collectives: sum_only=True (tpu/axon backend — "
                 "int64 pmax may not lower; using all_gather max)")
    return val


def solve_mesh(n_devices: Optional[int] = None,
               devices=None) -> Mesh:
    """A 1-D mesh over the type-parallel axis."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                devices = jax.devices("cpu")
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), axis_names=(AXIS,))


def _pad_types(inp: KernelInputs, n_shards: int) -> Tuple[KernelInputs, int]:
    """Pad the type axis to a multiple of the shard count (host-side
    numpy — runs before any device placement). Padded types have zero
    allocatable and no offerings -> never candidates."""
    T = inp.A.shape[0]
    Tp = ((T + n_shards - 1) // n_shards) * n_shards
    if Tp == T:
        return inp, T
    pad = Tp - T

    def padT0(a):  # type axis first
        a = np.asarray(a)
        return np.concatenate(
            [a, np.zeros((pad,) + a.shape[1:], a.dtype)], axis=0)

    def padT1(a):  # type axis second
        a = np.asarray(a)
        return np.concatenate(
            [a, np.zeros(a.shape[:1] + (pad,) + a.shape[2:], a.dtype)],
            axis=1)

    return inp._replace(A=padT0(inp.A), avail_zc=padT0(inp.avail_zc),
                        F=padT1(inp.F), pool_types=padT1(inp.pool_types)), T


def _input_specs(has_mv: bool) -> KernelInputs:
    """Partition specs per kernel input: type-axis sharded tensors vs the
    replicated carry-adjacent state (module docstring)."""
    repl = PS()
    return KernelInputs(
        A=PS(AXIS, None), avail_zc=PS(AXIS, None),
        R=repl, n=repl, F=PS(None, AXIS), agz=repl, agc=repl,
        admit=repl, daemon=repl,
        pool_types=PS(None, AXIS), pool_agz=repl, pool_agc=repl,
        pool_limit=repl, pool_used0=repl,
        ex_alloc=repl, ex_used0=repl, ex_compat=repl,
        # pair type indices are global; the kernel localizes per shard
        mv_floor=repl if has_mv else None,
        mv_pairs_t=repl if has_mv else None,
        mv_pairs_v=repl if has_mv else None)


@partial(jax.jit,
         static_argnames=("n_max", "E", "P", "V", "mesh", "sum_only"))
def _solve_sharded(inp: KernelInputs, n_max: int, E: int, P: int,
                   mesh: Mesh, V: int = 0, sum_only: bool = False):
    try:
        from jax import shard_map as _smap

        def shard_map(f, mesh, in_specs, out_specs):
            # the replication checker can't see through lax.pmax-into-
            # replicated-arithmetic; disable it (API name varies by version)
            try:
                return _smap(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
            except TypeError:
                return _smap(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map as _esmap

        def shard_map(f, mesh, in_specs, out_specs):
            return _esmap(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)

    repl = PS()
    in_specs = _input_specs(inp.mv_floor is not None)
    out_specs = (repl, repl, Carry(
        used=repl, types=PS(None, AXIS), zones=repl, ct=repl,
        pool=repl, alive=repl, num_nodes=repl, pool_used=repl))
    fn = shard_map(partial(_solve, n_max=n_max, E=E, P=P, axis=AXIS, V=V,
                           sum_only=sum_only),
                   mesh=mesh, in_specs=(in_specs,), out_specs=out_specs)
    return fn(inp)


def dispatch_mesh(arrays: dict, *, n_max: int, E: int, P: int, V: int,
                  ndev: int, cache: dict) -> dict:
    """The one mesh-dispatch implementation shared by the local solver
    (TPUSolver._dispatch_mesh) and the sidecar server: build/reuse the
    mesh (cache key: device count), run the type-parallel solve, and
    return the carry as the same dict shape as hostpack.unpack_outputs1
    — so the two paths can never drift apart."""
    mesh = cache.get("mesh")
    if mesh is None or mesh.devices.size != ndev:
        mesh = cache["mesh"] = solve_mesh(ndev)
    takes, leftover, carry = solve_scan_sharded(
        KernelInputs(**arrays), n_max=n_max, E=E, P=P, mesh=mesh, V=V)
    return dict(
        takes=np.asarray(takes), leftover=np.asarray(leftover),
        num_nodes=np.asarray([carry.num_nodes]),
        used=np.asarray(carry.used), pool=np.asarray(carry.pool),
        pool_used=np.asarray(carry.pool_used),
        types=np.asarray(carry.types), zones=np.asarray(carry.zones),
        ct=np.asarray(carry.ct), alive=np.asarray(carry.alive))


def solve_scan_sharded(inp: KernelInputs, n_max: int, E: int, P: int,
                       mesh: Mesh, V: int = 0,
                       sum_only: Optional[bool] = None
                       ) -> Tuple[jax.Array, jax.Array, Carry]:
    """Type-parallel solve over ``mesh``; same (takes, leftover, carry)
    contract as ops.ffd_jax.solve_scan, decisions identical."""
    if sum_only is None:
        # detection is a property of the mesh's backend: memoize so a
        # steady-state control loop doesn't re-sniff and re-log once per
        # solve (stable key — see _SUM_ONLY_CACHE)
        key = _mesh_key(mesh)
        cached = _SUM_ONLY_CACHE.get(key)
        if cached is None:
            cached = _needs_sum_only(mesh)
            _SUM_ONLY_CACHE[key] = cached
        sum_only = cached
    n_shards = mesh.devices.size
    padded, T = _pad_types(inp, n_shards)
    # explicit placement onto the mesh per spec — never the default device
    # (the default backend may be a different/broken platform)
    specs = _input_specs(padded.mv_floor is not None)
    padded = KernelInputs(*[
        None if x is None
        else jax.device_put(np.asarray(x), NamedSharding(mesh, s))
        for x, s in zip(padded, specs)])
    takes, leftover, carry = _solve_sharded(padded, n_max, E, P, mesh, V=V,
                                            sum_only=sum_only)
    if padded.A.shape[0] != T:
        carry = carry._replace(types=carry.types[:, :T])
    return takes, leftover, carry
