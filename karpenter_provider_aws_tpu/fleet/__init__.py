"""Horizontal solver fleet: N sidecar replicas as one logical solver.

- :mod:`.membership` — the replica registry (static endpoint config,
  per-replica health + circuit breakers + capability flags);
- :mod:`.ring` — rendezvous-hash affinity on (tenant, shape-class)
  with a deterministic failover order;
- :mod:`.fleetclient` — the :class:`FleetSolver` facade that follows
  the ring, re-primes the patch stream on every binding move, and
  keeps the single-sidecar degradation contract (host twin serves,
  decisions stay oracle-identical);
- :mod:`.meshgroup` — the coordinator role that forms worker
  processes into ONE logical distributed dp x tp solver (the vertical
  tier: one solve spanning processes, vs the horizontal tier's many
  solves across replicas), with supervised self-healing regroup;
- :mod:`.canary` — the tiny seeded solve that gates (re-)admission:
  a replica or regrouped mesh serves traffic only after its canary
  decisions byte-match the local oracle.

See docs/fleet.md for topology, affinity/failover semantics, the
shared compile-cache layout, and the re-prime cost model.
"""

from .canary import CANARY_SEED, CANARY_SHAPE, MESH_CANARY_SHAPE, run_canary
from .fleetclient import (AFFINITY, FAILOVER, REBALANCE, FleetSolver,
                          loopback_fleet)
from .membership import (ENDPOINTS_ENV, PROBE_TIMEOUT_ENV, FleetMembership,
                         Replica, endpoints_from_env, probe_timeout_s)
from .meshgroup import (HELLO_TIMEOUT_ENV, REPLY_TIMEOUT_ENV, MeshGroup,
                        hello_timeout_s, reply_timeout_s)
from .ring import owner, owner_order, shape_class

__all__ = [
    "FleetSolver", "FleetMembership", "MeshGroup", "Replica",
    "loopback_fleet", "owner", "owner_order", "shape_class",
    "endpoints_from_env", "ENDPOINTS_ENV", "AFFINITY", "FAILOVER",
    "REBALANCE", "run_canary", "CANARY_SHAPE", "MESH_CANARY_SHAPE",
    "CANARY_SEED", "PROBE_TIMEOUT_ENV", "probe_timeout_s",
    "HELLO_TIMEOUT_ENV", "REPLY_TIMEOUT_ENV", "hello_timeout_s",
    "reply_timeout_s",
]
