"""Horizontal solver fleet: N sidecar replicas as one logical solver.

- :mod:`.membership` — the replica registry (static endpoint config,
  per-replica health + circuit breakers + capability flags);
- :mod:`.ring` — rendezvous-hash affinity on (tenant, shape-class)
  with a deterministic failover order;
- :mod:`.fleetclient` — the :class:`FleetSolver` facade that follows
  the ring, re-primes the patch stream on every binding move, and
  keeps the single-sidecar degradation contract (host twin serves,
  decisions stay oracle-identical);
- :mod:`.meshgroup` — the coordinator role that forms worker
  processes into ONE logical distributed dp x tp solver (the vertical
  tier: one solve spanning processes, vs the horizontal tier's many
  solves across replicas).

See docs/fleet.md for topology, affinity/failover semantics, the
shared compile-cache layout, and the re-prime cost model.
"""

from .fleetclient import (AFFINITY, FAILOVER, REBALANCE, FleetSolver,
                          loopback_fleet)
from .membership import (ENDPOINTS_ENV, FleetMembership, Replica,
                         endpoints_from_env)
from .meshgroup import MeshGroup
from .ring import owner, owner_order, shape_class

__all__ = [
    "FleetSolver", "FleetMembership", "MeshGroup", "Replica",
    "loopback_fleet", "owner", "owner_order", "shape_class",
    "endpoints_from_env", "ENDPOINTS_ENV", "AFFINITY", "FAILOVER",
    "REBALANCE",
]
