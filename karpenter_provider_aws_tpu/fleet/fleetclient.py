"""``FleetSolver``: N solver sidecars behind one Solver facade.

A :class:`~..sidecar.client.RemoteSolver` whose wire binding follows
the rendezvous ring (fleet/ring.py) over a replica registry
(fleet/membership.py). Every dispatch resolves the (tenant,
shape-class) owner first; steady state that owner never changes, so a
tenant's warm ticks — hot kernels, bucketed shapes, server-resident
patch arena — stay pinned to one replica and ride deltas exactly as
against a single sidecar.

When the binding DOES move (owner parked → failover; membership
changed → rebalance), the patch stream is deliberately broken: the
rebind clears the endpoint-scoped state (capability flags + residency
prediction, sidecar/client.py bind_client) so the next dispatch rides
PR 10's ``no_resident`` path — ONE full Solve that re-primes the new
owner, never a stale delta. ``karpenter_solver_fleet_reprimes_total``
counts exactly those broken streams, which is what makes the fleet
chaos suite's "each residency break costs one full Solve" assertion
checkable from metrics alone.

Degradation is unchanged from the single-endpoint contract: a dead
pick costs that solve a wire attempt and the bit-identical host twin
serves it; the replica's breaker (its OWN — membership gives each
replica a policy) parks only its router evidence, and the next solve
fails over along the deterministic ring order.

Shared warmth: replicas started with the SAME ``compile_cache_dir``
(chart: the shared compile-cache volume) share one persistent XLA
cache and AOT store, so a scale-out replica's first solve of a shape
any replica has seen deserializes instead of compiling —
``loopback_fleet`` below wires that layout for tests and bench.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..sidecar.client import RemoteSolver
from .membership import FleetMembership
from .ring import owner_order, shape_class

#: routed_total reasons (the label is closed-vocabulary; docs/metrics.md)
AFFINITY = "affinity"
FAILOVER = "failover"
REBALANCE = "rebalance"


class FleetSolver(RemoteSolver):
    """RemoteSolver over a replica fleet with shape-affine routing."""

    name = "tpu-fleet"

    def __init__(self, endpoints: Optional[List[str]] = None,
                 n_max: int = 2048, backend: str = "auto",
                 token: Optional[str] = None,
                 root_cert: Optional[bytes] = None,
                 tenant: Optional[str] = None,
                 membership: Optional[FleetMembership] = None,
                 metrics=None, **membership_kw):
        if membership is None:
            membership = FleetMembership(
                endpoints, token=token, root_cert=root_cert,
                tenant=tenant, metrics=metrics, **membership_kw)
        addrs = membership.addresses()
        if not addrs:
            raise ValueError("FleetSolver needs at least one endpoint "
                             "(arg, SOLVER_FLEET_ENDPOINTS, or "
                             "SOLVER_SIDECAR_ADDRESS)")
        first = membership.get(addrs[0])
        super().__init__(first.address, n_max=n_max, client=first.client,
                         backend=backend)
        self.metrics = metrics
        self.tenant = tenant or "default"
        self._fleet = membership
        membership.metrics = membership.metrics or metrics
        membership.router = self._router
        membership._gauge()
        self._bound: str = first.address
        self._bound_reason: str = AFFINITY
        #: True once a SolvePatch landed on the current binding — i.e.
        #: the bound replica actually holds our arena resident. A rebind
        #: that breaks an active stream is a residency break: count it.
        self._stream_active = False
        #: False until the first dispatch consults the ring: the move
        #: OFF the arbitrary constructor binding onto the ring owner is
        #: the affinity placement itself, not a rebalance
        self._ring_seen = False
        #: False until the current binding passed a canary-gated probe
        #: (fleet/membership.py): the constructor binds blind, so the
        #: first owner resolution must admit even a non-moving binding
        self._admitted = False

    # -- routing ---------------------------------------------------------
    def _count_routed(self, replica: str, reason: str) -> None:
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_fleet_routed_total",
                             labels={"replica": replica,
                                     "reason": reason})

    def _rebind(self, address: str, reason: str) -> None:
        """Move the wire binding. The handoff is deliberate and paid in
        the open: endpoint-scoped state cleared, one Info ping against
        the new owner (capability resolution doubles as the health
        verdict), and — when the old binding carried a live patch
        stream — one counted re-prime that the next dispatch will pay
        as a full Solve."""
        t0 = time.perf_counter()
        rep = self._fleet.get(address)
        broke_stream = self._stream_active or self._patch_srv is not None
        self._stream_active = False
        ok = self.bind_client(rep.client)
        rep.healthy = ok
        rep.last_ping_s = time.monotonic()
        self._bound = address
        self._bound_reason = reason
        if self.metrics is not None:
            self.metrics.observe("karpenter_solver_fleet_handoff_ms",
                                 (time.perf_counter() - t0) * 1e3)
            if broke_stream:
                self.metrics.inc("karpenter_solver_fleet_reprimes_total")

    def _ensure_owner(self, statics: Dict[str, int]) -> None:
        """Resolve the (tenant, shape-class) owner and rebind if it is
        not the current peer. Called at the top of every dispatch —
        cheap (one blake2b per replica) relative to a wire round trip."""
        fleet = self._fleet
        addrs = fleet.addresses()
        if not addrs:
            # membership flapped to empty: keep the current binding —
            # its failures degrade to the host twin like any dead peer
            self._count_routed(self._bound, self._bound_reason)
            return
        order = owner_order(addrs, self.tenant, shape_class(statics))
        candidate = None
        for ep in order:
            if not fleet.routable(ep):
                continue
            if ep == self._bound and self._admitted:
                candidate = ep
                break
            # canary-gated (re-)admission: before the binding lands on
            # a peer it must answer Info AND return oracle-identical
            # canary decisions (fleet/canary.py). A failed verdict
            # records unhealthy/quarantined and the ring walks on; the
            # admitted steady state pays nothing extra
            if fleet.probe(ep):
                if ep == self._bound:
                    self._admitted = True
                candidate = ep
                break
        if candidate is None:
            # the whole fleet is parked: stay put; breakers half-open on
            # their own cooldown and the host twin serves meanwhile. A
            # QUARANTINED binding is stricter than parked: its wire
            # replies still parse — staying put would SERVE the wrong
            # decisions — so the liveness cache goes dark and the
            # bit-identical host twin takes every solve until a canary
            # re-admits someone
            rep = fleet._replicas.get(self._bound)
            if rep is not None and rep.quarantined \
                    and self._router.alive is not None:
                self._router.alive.mark_failed()
                self._admitted = False
            self._count_routed(self._bound, self._bound_reason)
            return
        if candidate == self._bound and self._bound in addrs:
            if self._caps_at is None:
                # first dispatch under this binding: resolve the peer's
                # capabilities now so warm ticks enter the delta wire
                # (a plain RemoteSolver gets this from its alive probe)
                self._ping()
            if candidate == order[0]:
                self._bound_reason = AFFINITY
            self._ring_seen = True
            self._count_routed(self._bound, self._bound_reason)
            return
        prev = self._bound
        if not self._ring_seen:
            # the very first ring consult: this IS the affinity
            # placement, whatever the constructor happened to bind
            reason = AFFINITY
        elif prev in addrs and not fleet.routable(prev):
            reason = FAILOVER
        else:
            # planned movement: the ring changed under us (join/leave),
            # the true owner recovered, or this shape class simply
            # hashes elsewhere than the last one
            reason = REBALANCE
        self._ring_seen = True
        self._rebind(candidate, reason)
        self._admitted = True
        self._count_routed(candidate, reason)

    # -- dispatch choke points -------------------------------------------
    def _dispatch(self, buf, **statics):
        self._ensure_owner(statics)
        return super()._dispatch(buf, **statics)

    def _dispatch_many(self, bufs, **statics):
        self._ensure_owner(statics)
        return super()._dispatch_many(bufs, **statics)

    def _dispatch_pruned(self, buf, **statics):
        self._ensure_owner(statics)
        return super()._dispatch_pruned(buf, **statics)

    def _dispatch_topo(self, arrays, rows, statics, cache=None):
        self._ensure_owner(statics)
        return super()._dispatch_topo(arrays, rows, statics, cache=cache)

    def dispatch_subsets(self, arrays, **kw):
        self._ensure_owner({k: kw[k] for k in ("n_max", "E", "P")
                            if k in kw})
        return super().dispatch_subsets(arrays, **kw)

    def _on_breaker_transition(self, old: str, new: str) -> None:
        """The bound replica's breaker opened: park ITS router evidence
        and fail over — the FLEET is alive as long as any replica is
        routable, so the liveness cache only goes dark when the last
        one parks (the single-endpoint contract marks it failed
        immediately; here that would blind the solve path to the
        healthy peers for a whole recheck window)."""
        from ..sidecar.resilience import OPEN
        if new == OPEN:
            ep = self._router.endpoint
            if ep is not None:
                self._router.park_dev(endpoint=ep)
            others = [a for a in self._fleet.addresses()
                      if a != self._bound and self._fleet.routable(a)]
            if not others and self._router.alive is not None:
                self._router.alive.mark_failed()
            return
        super()._on_breaker_transition(old, new)

    def _dispatch_patch(self, plan: dict):
        out = super()._dispatch_patch(plan)
        if out is not None:
            self._stream_active = True
        return out

    def close(self) -> None:
        self._fleet.close()


def loopback_fleet(n: int, *, compile_cache_dir: Optional[str] = None,
                   metrics=None, tenant: Optional[str] = None,
                   backend: str = "jax", n_max: int = 2048,
                   server_kw: Optional[dict] = None,
                   **solver_kw):
    """N in-process replicas sharing ONE compile-cache/AOT directory
    (the chart's shared-volume layout, minus the pod boundary) behind a
    FleetSolver — the harness tests/bench drive. Returns
    ``(servers, solver)``; the caller owns shutdown (``solver.close()``
    then ``srv.stop()`` each)."""
    from ..sidecar.server import SolverServer
    servers = []
    kw = dict(server_kw or {})
    if compile_cache_dir is not None:
        kw.setdefault("compile_cache_dir", compile_cache_dir)
    for _ in range(n):
        servers.append(SolverServer(metrics=metrics, **kw).start())
    solver = FleetSolver([s.address for s in servers], n_max=n_max,
                         backend=backend, tenant=tenant,
                         metrics=metrics, **solver_kw)
    return servers, solver
