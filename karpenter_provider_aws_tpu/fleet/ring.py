"""Rendezvous-hash (HRW) routing on (tenant, shape-class).

The fleet's whole value rests on affinity: a tenant's hot XLA kernels,
its bucketed shapes, and its server-resident patch arena all live on the
replica that served its last tick. Rendezvous hashing gives exactly the
placement properties that stack needs:

- deterministic: every client computes the same owner from the same
  membership list — no coordination, no shared state, no leader;
- minimal disruption: adding/removing one replica re-homes only the
  keys that hashed to it (a mod-N ring would re-home nearly all of
  them, breaking every tenant's patch stream on every scale event);
- a TOTAL preference order per key, not just a winner: when the owner
  is parked, every client agrees on the SAME next replica, so failover
  re-primes once fleet-wide instead of scattering a tenant's arena
  across whichever replica each client happened to pick.

Scores come from blake2b (hashlib), never Python ``hash()``:
PYTHONHASHSEED makes ``hash()`` differ per process, and two control
planes disagreeing on ownership is precisely the split-brain this
module exists to prevent.
"""

from __future__ import annotations

import hashlib
from typing import Dict, Iterable, List, Optional, Tuple


def shape_class(statics: Dict[str, int]) -> Tuple[int, ...]:
    """The affinity key's shape half: the padded statics tuple that also
    keys the XLA compile cache and the server's resident-arena table
    (PATCH_LAYOUT_KEYS). Two solves in the same shape class share a
    compiled kernel and a patch arena — the router must keep them on
    one replica; two classes may land anywhere."""
    from ..sidecar.server import PATCH_LAYOUT_KEYS
    return tuple(int(statics.get(k, 0)) for k in PATCH_LAYOUT_KEYS)


def _score(endpoint: str, key: bytes) -> int:
    h = hashlib.blake2b(digest_size=8)
    h.update(endpoint.encode("utf-8", "surrogatepass"))
    h.update(b"\x00")
    h.update(key)
    return int.from_bytes(h.digest(), "big")


def owner_order(endpoints: Iterable[str], tenant: Optional[str],
                shape: Tuple[int, ...]) -> List[str]:
    """Full HRW ranking of ``endpoints`` for (tenant, shape-class):
    element 0 is the affinity owner, the rest the deterministic
    failover order. Ties (astronomically unlikely at 64 bits) break on
    the endpoint string so the order is total either way."""
    key = repr((tenant or "default", tuple(shape))).encode()
    return sorted(endpoints,
                  key=lambda ep: (_score(ep, key), ep), reverse=True)


def owner(endpoints: Iterable[str], tenant: Optional[str],
          shape: Tuple[int, ...]) -> Optional[str]:
    order = owner_order(endpoints, tenant, shape)
    return order[0] if order else None
