"""Replica registry for the solver fleet.

One :class:`FleetMembership` holds the fleet's wire state: a
:class:`SolverClient` per replica (each with its OWN
:class:`~..sidecar.resilience.ResiliencePolicy` — one replica's
consecutive failures must trip one replica's breaker, never the
fleet's), per-replica health from the existing Info ping, and the
capability flags that ping resolved (``patch``/``batch``/``subsets``/
``pruned``). The membership list itself is static config — a comma-
separated endpoint list from flags or ``SOLVER_FLEET_ENDPOINTS`` — by
design: per-replica addressing comes from the chart's headless Service
(stable DNS names per ordinal), so the Helm values ARE the membership
and no discovery protocol is needed. ``add``/``remove`` exist for the
control plane that re-renders config (and for chaos tests to flap).

Health semantics mirror the single-sidecar posture: a replica is
ROUTABLE unless there is positive evidence against it — its breaker is
open, or its last Info ping failed. Unknown (never pinged) counts
routable: the bind-time ping resolves it, and a dead pick degrades that
one solve to the bit-identical host twin exactly like today's single
endpoint, never a crash.
"""

from __future__ import annotations

import os
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

from ..sidecar.client import SolverClient
from ..sidecar.resilience import OPEN, ResiliencePolicy

#: comma-separated replica endpoints, e.g.
#: "solver-0.solver:50151,solver-1.solver:50151"
ENDPOINTS_ENV = "SOLVER_FLEET_ENDPOINTS"

#: Info flags worth caching per replica (the fleet router consults
#: ``patch`` before expecting a delta stream to survive a failover;
#: ``mesh_group`` marks a replica that fronts a multi-process
#: distributed mesh — fleet/meshgroup.py)
_CAP_FLAGS = ("pruned", "batch", "subsets", "patch", "tenancy",
              "bucketed", "mesh_group")


class Replica:
    """One fleet member: its client (own channel, own policy/breaker),
    the last health verdict, and the capabilities its Info advertised."""

    def __init__(self, address: str, client: SolverClient):
        self.address = address
        self.client = client
        #: None = never probed (routable), True/False = last verdict
        self.healthy: Optional[bool] = None
        self.caps: Dict[str, bool] = {}
        self.last_ping_s: float = 0.0

    @property
    def policy(self) -> ResiliencePolicy:
        return self.client.policy

    @property
    def parked(self) -> bool:
        return self.policy.breaker.state == OPEN


class FleetMembership:
    def __init__(self, endpoints: Optional[List[str]] = None, *,
                 token: Optional[str] = None,
                 root_cert: Optional[bytes] = None,
                 tenant: Optional[str] = None,
                 policy_factory: Optional[
                     Callable[[str], ResiliencePolicy]] = None,
                 clients: Optional[Dict[str, SolverClient]] = None,
                 metrics=None):
        """``clients`` lets tests hand in pre-built (fault-wrapped)
        SolverClients per address; anything not covered is constructed
        here with its own fresh policy (``policy_factory(address)``
        when given — chaos tests use it to seed small breakers)."""
        if endpoints is None:
            endpoints = endpoints_from_env()
        self._token = token
        self._root_cert = root_cert
        self._tenant = tenant
        self._policy_factory = policy_factory
        self.metrics = metrics
        #: set by FleetSolver so a replica's breaker parks only ITS
        #: router evidence (solver/route.py park_dev(endpoint=...))
        self.router = None
        self._replicas: "OrderedDict[str, Replica]" = OrderedDict()
        for ep in endpoints:
            self.add(ep, client=(clients or {}).get(ep))
        self._gauge()

    # -- config ----------------------------------------------------------
    def _build_client(self, address: str) -> SolverClient:
        policy = self._policy_factory(address) \
            if self._policy_factory is not None else None
        return SolverClient(address, token=self._token,
                            root_cert=self._root_cert, policy=policy,
                            tenant=self._tenant)

    def _gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge("karpenter_solver_fleet_replicas",
                                   float(len(self._replicas)))

    # -- membership ------------------------------------------------------
    def addresses(self) -> List[str]:
        return list(self._replicas)

    def get(self, address: str) -> Replica:
        return self._replicas[address]

    def add(self, address: str,
            client: Optional[SolverClient] = None) -> Replica:
        if address in self._replicas:
            return self._replicas[address]
        rep = Replica(address, client or self._build_client(address))
        self._replicas[address] = rep

        def _on_breaker(old: str, new: str, rep=rep) -> None:
            from ..sidecar.resilience import CLOSED
            if new == OPEN:
                rep.healthy = False
                if self.router is not None:
                    self.router.park_dev(endpoint=rep.address)
            elif new == CLOSED and old != CLOSED:
                # transport recovered; capabilities may have changed
                # across the restart — unknown until the next bind pings
                rep.healthy = None

        rep.policy.breaker.on_transition.append(_on_breaker)
        self._gauge()
        return rep

    def remove(self, address: str) -> None:
        """Drop a replica from the membership (config re-render, chaos
        flap). Its router evidence is forgotten so the aggregate
        fallback never averages in a peer that left; the client stays
        open — the caller that handed it in owns its lifecycle."""
        rep = self._replicas.pop(address, None)
        if rep is None:
            return
        if self.router is not None:
            self.router.forget_endpoint(address)
        self._gauge()

    # -- health ----------------------------------------------------------
    def routable(self, address: str) -> bool:
        rep = self._replicas.get(address)
        if rep is None:
            return False
        return not rep.parked and rep.healthy is not False

    def alive(self) -> List[str]:
        return [a for a in self._replicas if self.routable(a)]

    def probe(self, address: str, timeout: float = 5.0) -> bool:
        """One Info round trip against a replica: records health AND
        the capability flags. Any failure is a False verdict, never an
        exception (same contract as RemoteSolver._ping)."""
        rep = self._replicas[address]
        try:
            info = rep.client.info(timeout=timeout)
            devices = info.get("devices")
            ok = isinstance(devices, int) and devices >= 1
        except Exception:
            info, ok = {}, False
        rep.healthy = ok
        rep.last_ping_s = time.monotonic()
        if ok:
            rep.caps = {k: bool(info.get(k, 0)) for k in _CAP_FLAGS}
        return ok

    def close(self) -> None:
        for rep in self._replicas.values():
            try:
                rep.client.close()
            except Exception:
                pass


def endpoints_from_env() -> List[str]:
    """Helm-friendly config: SOLVER_FLEET_ENDPOINTS is the comma-
    separated per-replica list (the headless Service's stable DNS
    names); a single-sidecar deployment that only sets
    SOLVER_SIDECAR_ADDRESS is a fleet of one."""
    raw = os.environ.get(ENDPOINTS_ENV, "")
    eps = [e.strip() for e in raw.split(",") if e.strip()]
    if eps:
        return eps
    single = os.environ.get("SOLVER_SIDECAR_ADDRESS", "").strip()
    return [single] if single else []
