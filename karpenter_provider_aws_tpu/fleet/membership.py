"""Replica registry for the solver fleet.

One :class:`FleetMembership` holds the fleet's wire state: a
:class:`SolverClient` per replica (each with its OWN
:class:`~..sidecar.resilience.ResiliencePolicy` — one replica's
consecutive failures must trip one replica's breaker, never the
fleet's), per-replica health from the existing Info ping, and the
capability flags that ping resolved (``patch``/``batch``/``subsets``/
``pruned``). The membership list itself is static config — a comma-
separated endpoint list from flags or ``SOLVER_FLEET_ENDPOINTS`` — by
design: per-replica addressing comes from the chart's headless Service
(stable DNS names per ordinal), so the Helm values ARE the membership
and no discovery protocol is needed. ``add``/``remove`` exist for the
control plane that re-renders config (and for chaos tests to flap).

Health semantics mirror the single-sidecar posture: a replica is
ROUTABLE unless there is positive evidence against it — its breaker is
open, or its last Info ping failed. Unknown (never pinged) counts
routable: the bind-time ping resolves it, and a dead pick degrades that
one solve to the bit-identical host twin exactly like today's single
endpoint, never a crash. Two refinements harden re-admission:

- a failed probe verdict AGES OUT after ``_UNHEALTHY_RECHECK_S`` — a
  transient blip must not remove a replica forever; the next owner
  resolution re-probes it for a fresh (canary-gated) verdict;
- ``probe`` is canary-gated (fleet/canary.py): after Info answers, a
  tiny seeded solve is byte-compared against the local oracle. A
  replica returning wrong-but-well-formed decisions is QUARANTINED —
  never routable, no aging out — until a later probe passes the canary
  or the control plane re-renders membership (remove/add). Quarantines
  count ``karpenter_solver_fleet_quarantined_total{replica}``; runbook
  entry in docs/troubleshooting.md.
"""

from __future__ import annotations

import logging
import os
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

log = logging.getLogger(__name__)

from ..sidecar.client import SolverClient
from ..sidecar.resilience import OPEN, ResiliencePolicy

#: comma-separated replica endpoints, e.g.
#: "solver-0.solver:50151,solver-1.solver:50151"
ENDPOINTS_ENV = "SOLVER_FLEET_ENDPOINTS"

#: probe Info deadline override (seconds); parse-validated like
#: KARP_MESH_DP2_MIN_SLOTS — unset/garbage/non-positive -> default
PROBE_TIMEOUT_ENV = "KARP_FLEET_PROBE_TIMEOUT_S"
_PROBE_TIMEOUT_S = 5.0

#: how long a failed probe verdict disqualifies a replica before the
#: next owner resolution may re-probe it
_UNHEALTHY_RECHECK_S = 30.0


def probe_timeout_s() -> float:
    env = os.environ.get(PROBE_TIMEOUT_ENV)
    if env:
        try:
            v = float(env)
            if v > 0:
                return v
        except ValueError:
            pass
    return _PROBE_TIMEOUT_S

#: Info flags worth caching per replica (the fleet router consults
#: ``patch`` before expecting a delta stream to survive a failover;
#: ``mesh_group`` marks a replica that fronts a multi-process
#: distributed mesh — fleet/meshgroup.py)
_CAP_FLAGS = ("pruned", "batch", "subsets", "patch", "tenancy",
              "bucketed", "mesh_group")


class Replica:
    """One fleet member: its client (own channel, own policy/breaker),
    the last health verdict, and the capabilities its Info advertised."""

    def __init__(self, address: str, client: SolverClient):
        self.address = address
        self.client = client
        #: None = never probed (routable), True/False = last verdict
        self.healthy: Optional[bool] = None
        #: True once a probe's canary came back well-formed but
        #: oracle-divergent: the replica answers the control plane but
        #: solves WRONG — never routable, and unlike plain
        #: unhealthiness this never ages out on its own
        self.quarantined: bool = False
        self.caps: Dict[str, bool] = {}
        self.last_ping_s: float = 0.0

    @property
    def policy(self) -> ResiliencePolicy:
        return self.client.policy

    @property
    def parked(self) -> bool:
        return self.policy.breaker.state == OPEN


class FleetMembership:
    def __init__(self, endpoints: Optional[List[str]] = None, *,
                 token: Optional[str] = None,
                 root_cert: Optional[bytes] = None,
                 tenant: Optional[str] = None,
                 policy_factory: Optional[
                     Callable[[str], ResiliencePolicy]] = None,
                 clients: Optional[Dict[str, SolverClient]] = None,
                 metrics=None, clock=None):
        """``clients`` lets tests hand in pre-built (fault-wrapped)
        SolverClients per address; anything not covered is constructed
        here with its own fresh policy (``policy_factory(address)``
        when given — chaos tests use it to seed small breakers)."""
        from ..sim.clock import monotonic_of
        if endpoints is None:
            endpoints = endpoints_from_env()
        #: probe-verdict aging reads through the clock seam so the
        #: endurance simulator can age out failed verdicts virtually
        self._clock = monotonic_of(clock)
        self._token = token
        self._root_cert = root_cert
        self._tenant = tenant
        self._policy_factory = policy_factory
        self.metrics = metrics
        #: set by FleetSolver so a replica's breaker parks only ITS
        #: router evidence (solver/route.py park_dev(endpoint=...))
        self.router = None
        self._replicas: "OrderedDict[str, Replica]" = OrderedDict()
        for ep in endpoints:
            self.add(ep, client=(clients or {}).get(ep))
        self._gauge()

    # -- config ----------------------------------------------------------
    def _build_client(self, address: str) -> SolverClient:
        policy = self._policy_factory(address) \
            if self._policy_factory is not None else None
        return SolverClient(address, token=self._token,
                            root_cert=self._root_cert, policy=policy,
                            tenant=self._tenant)

    def _gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge("karpenter_solver_fleet_replicas",
                                   float(len(self._replicas)))

    # -- membership ------------------------------------------------------
    def addresses(self) -> List[str]:
        return list(self._replicas)

    def get(self, address: str) -> Replica:
        return self._replicas[address]

    def add(self, address: str,
            client: Optional[SolverClient] = None) -> Replica:
        if address in self._replicas:
            return self._replicas[address]
        rep = Replica(address, client or self._build_client(address))
        self._replicas[address] = rep

        def _on_breaker(old: str, new: str, rep=rep) -> None:
            from ..sidecar.resilience import CLOSED
            if new == OPEN:
                rep.healthy = False
                if self.router is not None:
                    self.router.park_dev(endpoint=rep.address)
            elif new == CLOSED and old != CLOSED:
                # transport recovered; capabilities may have changed
                # across the restart — unknown until the next bind pings
                rep.healthy = None

        rep.policy.breaker.on_transition.append(_on_breaker)
        self._gauge()
        return rep

    def remove(self, address: str) -> None:
        """Drop a replica from the membership (config re-render, chaos
        flap). Its router evidence is forgotten so the aggregate
        fallback never averages in a peer that left; the client stays
        open — the caller that handed it in owns its lifecycle."""
        rep = self._replicas.pop(address, None)
        if rep is None:
            return
        if self.router is not None:
            self.router.forget_endpoint(address)
        self._gauge()

    # -- health ----------------------------------------------------------
    def routable(self, address: str) -> bool:
        rep = self._replicas.get(address)
        if rep is None:
            return False
        if rep.quarantined or rep.parked:
            return False
        if rep.healthy is False:
            # failed verdicts age out: a probe blip must not remove a
            # replica forever — past the recheck window the next owner
            # resolution re-probes it (canary-gated) for a fresh call
            return (self._clock() - rep.last_ping_s
                    >= _UNHEALTHY_RECHECK_S)
        return True

    def alive(self) -> List[str]:
        return [a for a in self._replicas if self.routable(a)]

    def probe(self, address: str, timeout: Optional[float] = None,
              canary: bool = True) -> bool:
        """One Info round trip + (by default) the seeded canary solve
        against a replica: records health, the capability flags, and
        the correctness verdict. Any failure is a False verdict, never
        an exception (same contract as RemoteSolver._ping). A
        well-formed but oracle-divergent canary reply quarantines the
        replica (module docstring); a passing one clears an existing
        quarantine — re-admission is earned, not timed out."""
        rep = self._replicas[address]
        if timeout is None:
            timeout = probe_timeout_s()
        try:
            info = rep.client.info(timeout=timeout)
            devices = info.get("devices")
            ok = isinstance(devices, int) and devices >= 1
        except Exception:
            info, ok = {}, False
        if ok and canary:
            from .canary import run_canary
            verdict = run_canary(rep.client)
            if verdict is False:
                if not rep.quarantined:
                    log.error("replica %s QUARANTINED: canary solve "
                              "returned well-formed but oracle-"
                              "divergent decisions (see "
                              "docs/troubleshooting.md)", address)
                    if self.metrics is not None:
                        self.metrics.inc(
                            "karpenter_solver_fleet_quarantined_total",
                            labels={"replica": address})
                rep.quarantined = True
                ok = False
            elif verdict is None:
                ok = False
            else:
                rep.quarantined = False
        rep.healthy = ok
        rep.last_ping_s = self._clock()
        if ok:
            rep.caps = {k: bool(info.get(k, 0)) for k in _CAP_FLAGS}
        return ok

    def close(self) -> None:
        for rep in self._replicas.values():
            try:
                rep.client.close()
            except Exception:
                pass


def endpoints_from_env() -> List[str]:
    """Helm-friendly config: SOLVER_FLEET_ENDPOINTS is the comma-
    separated per-replica list (the headless Service's stable DNS
    names); a single-sidecar deployment that only sets
    SOLVER_SIDECAR_ADDRESS is a fleet of one."""
    raw = os.environ.get(ENDPOINTS_ENV, "")
    eps = [e.strip() for e in raw.split(",") if e.strip()]
    if eps:
        return eps
    single = os.environ.get("SOLVER_SIDECAR_ADDRESS", "").strip()
    return [single] if single else []
