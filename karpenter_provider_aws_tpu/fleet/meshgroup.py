"""MeshGroup: worker sidecars formed into ONE logical distributed
solver.

The fleet's horizontal tier (membership/ring/fleetclient) scales
INDEPENDENT solves across replicas; this module scales ONE solve
across processes. A MeshGroup coordinator spawns (or, via the chart's
worker StatefulSet, is joined by) worker processes, forms them into a
single ``jax.distributed`` dp x tp mesh (parallel/distmesh.py), and
then routes work over a loopback control protocol:

- ``solve_seeded`` / ``solve_frame`` — one 2-D solve whose slot axis
  spans every process, each worker committing only its dp slab;
- ``solve_batch`` — SolveBatch lanes split round the processes, each
  worker running its lanes on its LOCAL devices (lanes are
  independent: zero collectives, linear scale-out).

Degradation keeps the PR 10 taxonomy: a lost worker makes the whole
distributed mesh unusable (a collective with a dead peer hangs, it
does not fail), so the coordinator kills the remaining workers, falls
back to the single-process mesh over its own devices, and forces
EXACTLY ONE full Solve (``dirty=None`` placement) before patch ticks
resume — lost residency is re-established once, then deltas flow
again. Decisions are identical in every mode by construction; the
fingerprint checks in hack/multihost.py prove it end to end.

Metrics (docs/metrics.md "Distributed mesh"):
``karpenter_solver_distmesh_processes`` gauge,
``karpenter_solver_distmesh_dispatch_total{mode}``,
``karpenter_solver_distmesh_patch_total{mode}`` (worker-side),
``karpenter_solver_distmesh_degraded_total{reason}``.
"""

from __future__ import annotations

import logging
import os
import socket
import subprocess
import sys
from typing import Dict, Optional

import numpy as np

log = logging.getLogger(__name__)

#: worker spawn/handshake deadline (cold python + jax import)
_HELLO_TIMEOUT_S = 120.0
#: per-command reply deadline: covers first-solve compile of the 2-D
#: kernel at ceiling shapes on virtual CPU devices
_REPLY_TIMEOUT_S = 900.0


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class MeshGroup:
    """Coordinator for one distributed solver (module docstring).

    ``workers`` is the number of EXTRA processes beyond the
    coordinator-side rank-0 worker; ``workers=0`` is the degenerate
    local mode (no subprocesses — dispatch goes straight to the
    single-process mesh), which is also what every degradation
    converges to."""

    def __init__(self, workers: int, local_devices: int = 8,
                 metrics=None, python: Optional[str] = None):
        self.workers = max(0, int(workers))
        self.local_devices = int(local_devices)
        self.metrics = metrics
        self._python = python or sys.executable
        self._procs: list = []
        self._socks: Dict[int, socket.socket] = {}
        self._degraded = False
        self._degrade_pending_full = False
        self._local_cache: dict = {}
        self.mesh_info: Optional[dict] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "MeshGroup":
        """Spawn rank 0..workers, collect hellos, form the jax mesh.
        Any failure here degrades instead of raising: a solver that
        cannot form its group still serves from the local mesh."""
        if self.workers <= 0:
            self._gauge_processes(1)
            return self
        try:
            self._start_distributed()
        except Exception:
            log.exception("mesh group formation failed; degrading to "
                          "the single-process mesh")
            self.degrade(reason="spawn_failed")
        return self

    def _start_distributed(self) -> None:
        nproc = self.workers + 1
        jax_port = _free_port()
        listener = socket.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(nproc)
        listener.settimeout(_HELLO_TIMEOUT_S)
        control = f"127.0.0.1:{listener.getsockname()[1]}"
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                            f"{self.local_devices}")
        env["PYTHONPATH"] = repo_root + os.pathsep + \
            env.get("PYTHONPATH", "")
        # KARP_DISTMESH_WORKER_LOGS=1 inherits worker stderr (debug)
        sink = None if os.environ.get("KARP_DISTMESH_WORKER_LOGS") \
            else subprocess.DEVNULL
        for i in range(nproc):
            self._procs.append(subprocess.Popen(
                [self._python, "-m",
                 "karpenter_provider_aws_tpu.parallel.distmesh",
                 "--worker", "--control", control, "--proc-id", str(i)],
                cwd=repo_root, env=env,
                stdout=sink, stderr=sink))
        try:
            for _ in range(nproc):
                conn, _addr = listener.accept()
                conn.settimeout(_REPLY_TIMEOUT_S)
                msg, _ = self._distmesh()._recv_msg(conn)
                self._socks[int(msg["hello"])] = conn
        finally:
            listener.close()
        infos = self._broadcast(lambda pid: ({
            "cmd": "mesh", "coordinator": f"127.0.0.1:{jax_port}",
            "num_processes": nproc, "process_id": pid,
            "local_devices": self.local_devices}, None))
        self.mesh_info = infos[0][0]
        self._gauge_processes(nproc)
        log.info("mesh group up: %d processes, %d devices, dp=%d tp=%d",
                 nproc, self.mesh_info["ndev"], self.mesh_info["dp"],
                 self.mesh_info["tp"])

    def stop(self) -> None:
        for pid, sock in list(self._socks.items()):
            try:
                self._distmesh()._send_msg(sock, {"cmd": "halt"})
                sock.close()
            except Exception:
                pass
        self._socks.clear()
        for p in self._procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
        self._procs = []

    def alive(self) -> bool:
        """True while the distributed mesh is usable: every worker
        process running and its control socket open."""
        return (bool(self._socks) and not self._degraded
                and all(p.poll() is None for p in self._procs))

    def degrade(self, reason: str = "worker_lost") -> None:
        """Collapse to the single-process mesh (PR 10 taxonomy): kill
        every worker — survivors would hang at their next collective
        waiting on the dead peer — and arm the one-full-Solve flag so
        the next dispatch re-establishes residency from scratch."""
        if self._degraded:
            return
        self._degraded = True
        self._degrade_pending_full = True
        for p in self._procs:
            try:
                p.kill()
            except Exception:
                pass
        for sock in self._socks.values():
            try:
                sock.close()
            except Exception:
                pass
        self._socks.clear()
        self._gauge_processes(1)
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_distmesh_degraded_total",
                             labels={"reason": reason})
        log.warning("mesh group degraded (%s): serving from the "
                    "single-process mesh; next solve is a full "
                    "placement", reason)

    # -- plumbing ----------------------------------------------------------

    @staticmethod
    def _distmesh():
        from ..parallel import distmesh
        return distmesh

    def _gauge_processes(self, n: int) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge("karpenter_solver_distmesh_processes",
                                   n)

    def _check(self) -> bool:
        """Poll worker liveness BEFORE dispatching: a dead peer must be
        caught here, where degrading is cheap, not inside a collective,
        where it is a hang."""
        if self._degraded or not self._socks:
            return False
        if any(p.poll() is not None for p in self._procs):
            self.degrade(reason="worker_lost")
            return False
        return True

    def _broadcast(self, make_msg):
        """Send make_msg(pid) to every worker, then collect every
        reply (send-all-then-recv-all: the SPMD solve only completes
        once every process has entered it). Any transport error or
        worker-reported failure degrades the group."""
        dm = self._distmesh()
        try:
            for pid in sorted(self._socks):
                msg, arrays = make_msg(pid)
                dm._send_msg(self._socks[pid], msg, arrays)
            replies = {}
            for pid in sorted(self._socks):
                reply, arrays = dm._recv_msg(self._socks[pid])
                if reply is None or not reply.get("ok"):
                    err = (reply or {}).get("error", "socket closed")
                    raise RuntimeError(f"worker {pid}: {err}")
                replies[pid] = (reply, arrays)
        except Exception:
            self.degrade(reason="worker_lost")
            raise
        return [replies[pid] for pid in sorted(replies)]

    # -- dispatch surfaces -------------------------------------------------

    def _dirty_for_local(self, dirty):
        """The one-full-Solve taxonomy: the first local dispatch after
        a degrade ignores the caller's dirty list (residency was lost
        with the workers), every later one honors it."""
        if self._degrade_pending_full:
            self._degrade_pending_full = False
            return None
        return dirty

    def _solve_local(self, arrays, statics, dirty, mode_label):
        from ..parallel.mesh import _pick_devices, dispatch_mesh
        ndev = len(_pick_devices())
        out = dispatch_mesh(arrays, n_max=statics["n_max"],
                            E=statics["E"], P=statics["P"], V=0,
                            ndev=ndev, cache=self._local_cache,
                            dirty=self._dirty_for_local(dirty),
                            metrics=self.metrics)
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_distmesh_dispatch_total",
                             labels={"mode": mode_label})
        return {"out": out,
                "fingerprint":
                    self._distmesh().result_fingerprint(out),
                "mode":
                    self._local_cache["last_placement"]["mode"],
                "distributed": False}

    def solve_seeded(self, shape: dict, seed: int, tick: int,
                     dirty=None, want_arrays: bool = False) -> dict:
        """One distributed solve of the deterministic tick workload
        (distmesh.tick_arrays): each worker regenerates its own slab —
        nothing bulk crosses the control wire. Falls back to the local
        mesh (full arrays, one process) when degraded."""
        statics = {k: shape[k] for k in ("n_max", "E", "P")}
        if not self._check():
            arrays, _ = self._distmesh().tick_arrays(shape, seed, tick)
            return self._solve_local(
                arrays, statics, dirty,
                "degraded" if self._degraded else "local")
        try:
            replies = self._broadcast(lambda pid: ({
                "cmd": "solve_seeded", "shape": shape, "seed": seed,
                "tick": tick, "dirty": dirty,
                "want_arrays": want_arrays and pid == 0}, None))
        except Exception:
            arrays, _ = self._distmesh().tick_arrays(shape, seed, tick)
            return self._solve_local(arrays, statics, dirty, "degraded")
        return self._collect(replies, "seeded", want_arrays)

    def solve_frame(self, arrays: dict, statics: dict,
                    dirty=None, want_arrays: bool = False) -> dict:
        """One distributed solve of caller-supplied arrays (the sidecar
        path — the frame already arrived whole over gRPC): slot tables
        are sliced per worker so each process still commits only its
        slab."""
        if not self._check():
            return self._solve_local(
                arrays, statics, dirty,
                "degraded" if self._degraded else "local")
        dm = self._distmesh()
        nproc = self.workers + 1
        dp = self.mesh_info["dp"]
        N = statics["E"] + statics["n_max"]
        Np = ((N + dp - 1) // dp) * dp

        def pad0(a, rows):
            a = np.asarray(a)
            out = np.zeros((rows,) + a.shape[1:], a.dtype)
            out[:a.shape[0]] = a
            return out

        ex_alloc = pad0(arrays["ex_alloc"], Np)
        ex_used0 = pad0(arrays["ex_used0"], Np)
        compat = np.asarray(arrays["ex_compat"])
        ex_compat = np.zeros(compat.shape[:1] + (Np,), compat.dtype)
        ex_compat[:, :compat.shape[1]] = compat
        repl = {k: np.asarray(v) for k, v in arrays.items()
                if k not in ("ex_alloc", "ex_used0", "ex_compat")
                and v is not None}

        def frame_for(pid):
            lo, hi = dm.local_slot_rows(Np, nproc, pid)
            payload = dict(repl)
            payload["ex_alloc"] = ex_alloc[lo:hi]
            payload["ex_used0"] = ex_used0[lo:hi]
            payload["ex_compat"] = ex_compat[:, lo:hi]
            slabs = {
                "ex_alloc": [lo, hi, 0, [Np, ex_alloc.shape[1]]],
                "ex_used0": [lo, hi, 0, [Np, ex_used0.shape[1]]],
                "ex_compat": [lo, hi, 1, [ex_compat.shape[0], Np]],
            }
            msg = {"cmd": "solve_frame", "dirty": dirty,
                   "want_arrays": want_arrays and pid == 0,
                   "slabs": slabs}
            msg.update({k: int(v) for k, v in statics.items()})
            return msg, payload

        try:
            replies = self._broadcast(frame_for)
        except Exception:
            return self._solve_local(arrays, statics, dirty, "degraded")
        return self._collect(replies, "frame", want_arrays)

    def _collect(self, replies, mode_label, want_arrays):
        fps = {r["fingerprint"] for r, _ in replies}
        if len(fps) != 1:
            # processes disagreeing on a replicated output is a
            # correctness emergency, not a retry case
            self.degrade(reason="fingerprint_split")
            raise RuntimeError(
                f"cross-process fingerprint mismatch: {sorted(fps)}")
        r0, arrays0 = replies[0]
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_distmesh_dispatch_total",
                             labels={"mode": mode_label})
        return {"out": arrays0 if want_arrays else None,
                "fingerprint": r0["fingerprint"], "mode": r0["mode"],
                "timing": r0.get("timing", {}),
                "wall_s": r0.get("wall_s"), "distributed": True}

    def solve_batch(self, stack: np.ndarray, kv: dict
                    ) -> Optional[np.ndarray]:
        """Route SolveBatch lanes across the group: contiguous lane
        spans per process, each solved on that worker's local devices,
        reassembled in order. Returns None when the group cannot serve
        (degraded / routing error) — the caller keeps its local path."""
        if not self._check():
            return None
        stack = np.asarray(stack)
        B = stack.shape[0]
        nproc = self.workers + 1
        spans = []
        base, extra = divmod(B, nproc)
        at = 0
        for pid in range(nproc):
            take = base + (1 if pid < extra else 0)
            spans.append((at, at + take))
            at += take

        def batch_for(pid):
            lo, hi = spans[pid]
            if hi == lo:  # empty span still needs a round trip: the
                # broadcast protocol is strict send-all/recv-all
                lo, hi = 0, 1
            return ({"cmd": "solve_batch",
                     "kv": {k: int(v) for k, v in kv.items()}},
                    {"stack": stack[lo:hi]})

        try:
            replies = self._broadcast(batch_for)
        except Exception:
            return None
        parts = []
        for pid, (_, arrays) in enumerate(replies):
            lo, hi = spans[pid]
            parts.append(arrays["out"][:hi - lo])
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_distmesh_dispatch_total",
                             labels={"mode": "batch"})
        return np.concatenate(parts, axis=0)

    def solve_oracle(self, shape: dict, seed: int, tick: int,
                     want_arrays: bool = False) -> dict:
        """The fingerprint baseline, computed in THIS process on one
        device via the shared dispatch (distmesh.oracle_out)."""
        dm = self._distmesh()
        arrays, statics = dm.tick_arrays(shape, seed, tick)
        out = dm.oracle_out(arrays, **statics)
        return {"out": out if want_arrays else None,
                "fingerprint": dm.result_fingerprint(out)}
