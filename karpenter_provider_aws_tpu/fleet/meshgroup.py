"""MeshGroup: worker sidecars formed into ONE logical distributed
solver.

The fleet's horizontal tier (membership/ring/fleetclient) scales
INDEPENDENT solves across replicas; this module scales ONE solve
across processes. A MeshGroup coordinator spawns (or, via the chart's
worker StatefulSet, is joined by) worker processes, forms them into a
single ``jax.distributed`` dp x tp mesh (parallel/distmesh.py), and
then routes work over a loopback control protocol:

- ``solve_seeded`` / ``solve_frame`` — one 2-D solve whose slot axis
  spans every process, each worker committing only its dp slab;
- ``solve_batch`` — SolveBatch lanes split round the processes, each
  worker running its lanes on its LOCAL devices (lanes are
  independent: zero collectives, linear scale-out).

Degradation keeps the PR 10 taxonomy: a lost worker makes the whole
distributed mesh unusable (a collective with a dead peer hangs, it
does not fail), so the coordinator kills the remaining workers, falls
back to the single-process mesh over its own devices, and forces
EXACTLY ONE full Solve (``dirty=None`` placement) before patch ticks
resume — lost residency is re-established once, then deltas flow
again. Decisions are identical in every mode by construction; the
fingerprint checks in hack/multihost.py prove it end to end.

Degradation is no longer terminal: a supervisor rides the dispatch
path (``_check``) and, after a degrade, reaps the dead group and
re-forms it with bounded exponential backoff — capped attempts, then
stay-degraded. A re-formed group serves traffic only after a seeded
canary solve fingerprints identical to the local CPU oracle
(canary-gated re-admission), and every (re)formation bumps a mesh
``epoch`` carried in every control frame and echoed in every worker
reply, so a zombie worker's late bytes from a prior epoch are
rejected, never merged. Workers are fresh processes, so the first
distributed solve after a regroup is naturally a full placement — the
one full Solve the residency break costs, same taxonomy as the
degrade itself. See docs/fleet.md "Recovery taxonomy".

Metrics (docs/metrics.md "Distributed mesh"):
``karpenter_solver_distmesh_processes`` gauge,
``karpenter_solver_distmesh_dispatch_total{mode}``,
``karpenter_solver_distmesh_patch_total{mode}`` (worker-side),
``karpenter_solver_distmesh_degraded_total{reason}``,
``karpenter_solver_distmesh_recovered_total{reason}``,
``karpenter_solver_distmesh_regroup_ms``,
``karpenter_solver_distmesh_stale_rejected_total``.
"""

from __future__ import annotations

import logging
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, Optional

import numpy as np

log = logging.getLogger(__name__)

#: worker spawn/handshake deadline (cold python + jax import)
_HELLO_TIMEOUT_S = 120.0
#: per-command reply deadline: covers first-solve compile of the 2-D
#: kernel at ceiling shapes on virtual CPU devices. Doubles as the
#: wedge watchdog: a worker whose socket stays open but whose solve
#: never returns trips this per-reply deadline instead of stalling
#: every subsequent tick.
_REPLY_TIMEOUT_S = 900.0

HELLO_TIMEOUT_ENV = "KARP_DISTMESH_HELLO_TIMEOUT_S"
REPLY_TIMEOUT_ENV = "KARP_DISTMESH_REPLY_TIMEOUT_S"
REGROUP_ATTEMPTS_ENV = "KARP_DISTMESH_REGROUP_ATTEMPTS"
REGROUP_BACKOFF_ENV = "KARP_DISTMESH_REGROUP_BACKOFF_S"

#: supervised regroup defaults: first attempt after the base backoff,
#: doubling per failure up to the cap, then stay-degraded for good
_REGROUP_ATTEMPTS = 3
_REGROUP_BACKOFF_S = 30.0
_REGROUP_BACKOFF_CAP_S = 300.0

#: bounded formation retries when the jax coordinator port raced
#: (_free_port TOCTOU: the port is bound, closed, and rebound later
#: inside worker 0 — a collision surfaces as a bind error in the
#: worker's mesh reply, not here)
_FORMATION_TRIES = 3
_PORT_RETRY_MARKERS = ("address already in use", "errno 98",
                       "eaddrinuse", "failed to bind")

#: how many frames to discard per worker while hunting the
#: current-epoch reply before declaring the socket poisoned
_STALE_REREADS = 4


def _env_float(name: str, default: float) -> float:
    """KARP_MESH_DP2_MIN_SLOTS-style parse validation: unset, garbage,
    or non-positive values fall back to the default, never a crash."""
    env = os.environ.get(name)
    if env:
        try:
            v = float(env)
            if v > 0:
                return v
        except ValueError:
            pass
    return default


def hello_timeout_s() -> float:
    return _env_float(HELLO_TIMEOUT_ENV, _HELLO_TIMEOUT_S)


def reply_timeout_s() -> float:
    return _env_float(REPLY_TIMEOUT_ENV, _REPLY_TIMEOUT_S)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class MeshGroup:
    """Coordinator for one distributed solver (module docstring).

    ``workers`` is the number of EXTRA processes beyond the
    coordinator-side rank-0 worker; ``workers=0`` is the degenerate
    local mode (no subprocesses — dispatch goes straight to the
    single-process mesh), which is also what every degradation
    converges to."""

    def __init__(self, workers: int, local_devices: int = 8,
                 metrics=None, python: Optional[str] = None,
                 hello_timeout_s: Optional[float] = None,
                 reply_timeout_s: Optional[float] = None,
                 regroup_attempts: Optional[int] = None,
                 regroup_backoff_s: Optional[float] = None,
                 clock=None):
        from ..sim.clock import monotonic_of
        #: the clock seam governs ONLY the regroup scheduling timers
        #: (degrade timestamps, backoff deadlines, outage accounting);
        #: _reap and socket timeouts stay wall-clock — they bound real
        #: OS processes and sockets, which do not run on virtual time
        self._clock = monotonic_of(clock)
        self.workers = max(0, int(workers))
        self.local_devices = int(local_devices)
        self.metrics = metrics
        self._python = python or sys.executable
        self.hello_timeout_s = float(hello_timeout_s) \
            if hello_timeout_s is not None else _env_float(
                HELLO_TIMEOUT_ENV, _HELLO_TIMEOUT_S)
        self.reply_timeout_s = float(reply_timeout_s) \
            if reply_timeout_s is not None else _env_float(
                REPLY_TIMEOUT_ENV, _REPLY_TIMEOUT_S)
        self.regroup_attempts = int(regroup_attempts) \
            if regroup_attempts is not None else int(_env_float(
                REGROUP_ATTEMPTS_ENV, _REGROUP_ATTEMPTS))
        self.regroup_backoff_s = float(regroup_backoff_s) \
            if regroup_backoff_s is not None else _env_float(
                REGROUP_BACKOFF_ENV, _REGROUP_BACKOFF_S)
        self._procs: list = []
        self._socks: Dict[int, socket.socket] = {}
        self._degraded = False
        self._degrade_pending_full = False
        self._local_cache: dict = {}
        self.mesh_info: Optional[dict] = None
        #: mesh epoch: bumped at every (re)formation attempt, carried
        #: in every control frame, echoed in every worker reply — the
        #: fence that keeps a prior group's zombie bytes out
        self.epoch = 0
        self._degrade_reason: Optional[str] = None
        self._degraded_at: Optional[float] = None
        #: monotonic deadline of the next supervised regroup attempt;
        #: None = no regroup pending (healthy, stopped, or given up)
        self._regroup_at: Optional[float] = None
        self._regroup_attempt = 0
        self._regroup_lock = threading.Lock()
        self._closed = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "MeshGroup":
        """Spawn rank 0..workers, collect hellos, form the jax mesh.
        Any failure here degrades instead of raising: a solver that
        cannot form its group still serves from the local mesh (and
        the supervisor keeps retrying formation with backoff)."""
        if self.workers <= 0:
            self._gauge_processes(1)
            return self
        try:
            self._form()
        except Exception:
            log.exception("mesh group formation failed; degrading to "
                          "the single-process mesh")
            self.degrade(reason="spawn_failed")
        return self

    def _form(self) -> None:
        """One group formation with bounded retry on coordinator-port
        bind collisions (the _free_port TOCTOU): the jax port is
        picked here but bound later inside worker 0, so a raced port
        surfaces as a bind failure in the mesh reply — retried with a
        fresh port instead of landing in spawn_failed forever."""
        last: Optional[Exception] = None
        for attempt in range(_FORMATION_TRIES):
            try:
                self._start_distributed()
                return
            except Exception as e:
                self._teardown_attempt()
                last = e
                text = repr(e).lower()
                if not any(m in text for m in _PORT_RETRY_MARKERS):
                    raise
                log.warning("mesh formation attempt %d raced the "
                            "coordinator port (%s); retrying with a "
                            "fresh one", attempt + 1, e)
        assert last is not None
        raise last

    def _start_distributed(self) -> None:
        self.epoch += 1
        nproc = self.workers + 1
        jax_port = _free_port()
        listener = socket.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(nproc)
        listener.settimeout(self.hello_timeout_s)
        control = f"127.0.0.1:{listener.getsockname()[1]}"
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                            f"{self.local_devices}")
        env["PYTHONPATH"] = repo_root + os.pathsep + \
            env.get("PYTHONPATH", "")
        # KARP_DISTMESH_WORKER_LOGS=1 inherits worker stderr (debug)
        sink = None if os.environ.get("KARP_DISTMESH_WORKER_LOGS") \
            else subprocess.DEVNULL
        for i in range(nproc):
            self._procs.append(subprocess.Popen(
                [self._python, "-m",
                 "karpenter_provider_aws_tpu.parallel.distmesh",
                 "--worker", "--control", control, "--proc-id", str(i)],
                cwd=repo_root, env=env,
                stdout=sink, stderr=sink))
        try:
            for _ in range(nproc):
                conn, _addr = listener.accept()
                conn.settimeout(self.reply_timeout_s)
                msg, _ = self._distmesh()._recv_msg(conn)
                self._socks[int(msg["hello"])] = conn
        finally:
            listener.close()
        infos = self._broadcast(lambda pid: ({
            "cmd": "mesh", "coordinator": f"127.0.0.1:{jax_port}",
            "num_processes": nproc, "process_id": pid,
            "local_devices": self.local_devices}, None),
            degrade_on_error=False)
        self.mesh_info = infos[0][0]
        self._gauge_processes(nproc)
        log.info("mesh group up: %d processes, %d devices, dp=%d "
                 "tp=%d, epoch=%d", nproc, self.mesh_info["ndev"],
                 self.mesh_info["dp"], self.mesh_info["tp"], self.epoch)

    def stop(self) -> None:
        self._closed = True
        self._regroup_at = None
        for pid, sock in list(self._socks.items()):
            try:
                self._distmesh()._send_msg(sock, {"cmd": "halt"})
                sock.close()
            except Exception:
                pass
        self._socks.clear()
        # one shared deadline for the whole set: an N-worker shutdown
        # is bounded by ONE grace window, not N serial waits
        self._reap(self._procs, timeout=10.0)
        self._procs = []

    @staticmethod
    def _reap(procs, timeout: float = 10.0) -> None:
        """Wait for every process under ONE shared deadline, then
        escalate the stragglers to kill() and collect them — no
        zombies, no unbounded shutdown."""
        deadline = time.monotonic() + timeout
        pending = [p for p in procs if p.poll() is None]
        while pending and time.monotonic() < deadline:
            time.sleep(0.02)
            pending = [p for p in pending if p.poll() is None]
        for p in pending:
            try:
                p.kill()
            except Exception:
                pass
        for p in pending:
            try:
                p.wait(timeout=5.0)
            except Exception:
                pass

    def alive(self) -> bool:
        """True while the distributed mesh is usable: every worker
        process running and its control socket open."""
        return (bool(self._socks) and not self._degraded
                and all(p.poll() is None for p in self._procs))

    def degrade(self, reason: str = "worker_lost") -> None:
        """Collapse to the single-process mesh (PR 10 taxonomy): kill
        AND reap every worker — survivors would hang at their next
        collective waiting on the dead peer — arm the one-full-Solve
        flag so the next dispatch re-establishes residency from
        scratch, and schedule the supervised regroup."""
        if self._degraded:
            return
        self._degraded = True
        self._degrade_pending_full = True
        self._degrade_reason = reason
        self._degraded_at = self._clock()
        for p in self._procs:
            try:
                p.kill()
            except Exception:
                pass
        for sock in self._socks.values():
            try:
                sock.close()
            except Exception:
                pass
        self._socks.clear()
        self._reap(self._procs, timeout=5.0)
        self._procs = []
        self.mesh_info = None
        self._gauge_processes(1)
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_distmesh_degraded_total",
                             labels={"reason": reason})
        self._regroup_attempt = 0
        if (self.workers > 0 and not self._closed
                and self.regroup_attempts > 0):
            self._regroup_at = self._clock() + self.regroup_backoff_s
            log.warning("mesh group degraded (%s): serving from the "
                        "single-process mesh; next solve is a full "
                        "placement, regroup scheduled in %.1fs",
                        reason, self.regroup_backoff_s)
        else:
            self._regroup_at = None
            log.warning("mesh group degraded (%s): serving from the "
                        "single-process mesh; next solve is a full "
                        "placement", reason)

    # -- plumbing ----------------------------------------------------------

    @staticmethod
    def _distmesh():
        from ..parallel import distmesh
        return distmesh

    def _gauge_processes(self, n: int) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge("karpenter_solver_distmesh_processes",
                                   n)

    def _check(self) -> bool:
        """Poll worker liveness BEFORE dispatching: a dead peer must be
        caught here, where degrading is cheap, not inside a collective,
        where it is a hang. While degraded, this is also the supervisor
        tick that attempts the scheduled regroup."""
        if self._degraded:
            if not self._maybe_regroup():
                return False
        if not self._socks:
            return False
        if any(p.poll() is not None for p in self._procs):
            self.degrade(reason="worker_lost")
            return False
        return True

    # -- supervised regroup ------------------------------------------------

    def _teardown_attempt(self) -> None:
        """Reap one failed formation/regroup attempt's processes and
        sockets WITHOUT touching the degradation state — the caller
        decides whether to retry, reschedule, or give up."""
        for sock in self._socks.values():
            try:
                sock.close()
            except Exception:
                pass
        self._socks.clear()
        for p in self._procs:
            try:
                p.kill()
            except Exception:
                pass
        self._reap(self._procs, timeout=5.0)
        self._procs = []
        self.mesh_info = None

    def heal_async(self) -> None:
        """Sidecar wiring: kick the supervised regroup WITHOUT
        blocking the caller — Info and solve RPCs must not stall
        behind a worker respawn. No-op unless a regroup is due; the
        non-blocking lock in ``_maybe_regroup`` keeps concurrent kicks
        from double-forming."""
        if (self._regroup_at is None or self._closed
                or self._clock() < self._regroup_at):
            return
        threading.Thread(target=self._maybe_regroup,
                         name="meshgroup-regroup", daemon=True).start()

    def _maybe_regroup(self) -> bool:
        """One supervisor tick: if the scheduled regroup deadline has
        passed, re-form the group and canary-gate it. Returns True
        when the group recovered (the caller may dispatch distributed
        again). Failed attempts back off exponentially; after
        ``regroup_attempts`` failures the group stays degraded."""
        if (self._regroup_at is None or self._closed
                or self.workers <= 0
                or self._clock() < self._regroup_at):
            return False
        if not self._regroup_lock.acquire(blocking=False):
            return False
        try:
            return self._regroup_once()
        finally:
            self._regroup_lock.release()

    def _regroup_once(self) -> bool:
        self._regroup_attempt += 1
        attempt = self._regroup_attempt
        try:
            self._form()
            if not self._canary_group():
                raise RuntimeError("regroup canary diverged from the "
                                   "local oracle")
        except Exception as e:
            self._teardown_attempt()
            self._gauge_processes(1)
            if attempt >= self.regroup_attempts:
                self._regroup_at = None
                log.error("mesh regroup attempt %d/%d failed (%s); "
                          "staying degraded", attempt,
                          self.regroup_attempts, e)
            else:
                delay = min(self.regroup_backoff_s * (2 ** attempt),
                            _REGROUP_BACKOFF_CAP_S)
                self._regroup_at = self._clock() + delay
                log.warning("mesh regroup attempt %d/%d failed (%s); "
                            "next attempt in %.1fs", attempt,
                            self.regroup_attempts, e, delay)
            return False
        reason = self._degrade_reason or "unknown"
        now = self._clock()
        outage_s = now - (self._degraded_at or now)
        self._degraded = False
        self._degrade_reason = None
        self._degraded_at = None
        self._regroup_at = None
        self._regroup_attempt = 0
        if self.metrics is not None:
            self.metrics.inc(
                "karpenter_solver_distmesh_recovered_total",
                labels={"reason": reason})
            self.metrics.observe(
                "karpenter_solver_distmesh_regroup_ms", outage_s * 1e3)
        log.info("mesh group recovered from %s after %.1fs (attempt "
                 "%d, epoch %d): canary fingerprint matches the local "
                 "oracle; distributed dispatch resumes", reason,
                 outage_s, attempt, self.epoch)
        return True

    def _canary_group(self) -> bool:
        """Canary-gated re-admission for the JUST-FORMED group: one
        tiny seeded solve through every worker (a throwaway cache on
        their side — production residency is untouched), fingerprint-
        checked against the local CPU oracle. A group that answers the
        control plane but solves wrong never serves traffic."""
        from .canary import CANARY_SEED, MESH_CANARY_SHAPE
        replies = self._broadcast(lambda pid: ({
            "cmd": "canary", "shape": MESH_CANARY_SHAPE,
            "seed": CANARY_SEED, "tick": 0}, None),
            degrade_on_error=False)
        fps = {r["fingerprint"] for r, _ in replies}
        want = self.solve_oracle(MESH_CANARY_SHAPE, seed=CANARY_SEED,
                                 tick=0)["fingerprint"]
        return fps == {want}

    def _broadcast(self, make_msg, degrade_on_error: bool = True):
        """Send make_msg(pid) to every worker, then collect every
        reply (send-all-then-recv-all: the SPMD solve only completes
        once every process has entered it). Every outgoing frame
        carries the mesh epoch and every reply must echo it — a
        zombie's late bytes from a prior epoch are discarded, never
        merged. A reply-deadline timeout is the wedge signature
        (socket alive, solve never returns) and degrades as
        ``worker_wedged``; any other transport error or
        worker-reported failure degrades as ``worker_lost``."""
        dm = self._distmesh()
        try:
            for pid in sorted(self._socks):
                msg, arrays = make_msg(pid)
                msg.setdefault("epoch", self.epoch)
                dm._send_msg(self._socks[pid], msg, arrays)
            replies = {}
            for pid in sorted(self._socks):
                for _ in range(_STALE_REREADS):
                    reply, arrays = dm._recv_msg(self._socks[pid])
                    ep = None if reply is None else reply.get("epoch")
                    if ep is None or int(ep) == self.epoch:
                        break
                    log.warning("worker %d: rejected stale reply from "
                                "mesh epoch %s (current %d)", pid, ep,
                                self.epoch)
                    if self.metrics is not None:
                        self.metrics.inc(
                            "karpenter_solver_distmesh_"
                            "stale_rejected_total")
                else:
                    raise RuntimeError(
                        f"worker {pid}: nothing but stale-epoch "
                        f"replies after {_STALE_REREADS} frames")
                if reply is None or not reply.get("ok"):
                    err = (reply or {}).get("error", "socket closed")
                    raise RuntimeError(f"worker {pid}: {err}")
                replies[pid] = (reply, arrays)
        except socket.timeout:
            if degrade_on_error:
                self.degrade(reason="worker_wedged")
            raise
        except Exception:
            if degrade_on_error:
                self.degrade(reason="worker_lost")
            raise
        return [replies[pid] for pid in sorted(replies)]

    # -- dispatch surfaces -------------------------------------------------

    def _dirty_for_local(self, dirty):
        """The one-full-Solve taxonomy: the first local dispatch after
        a degrade ignores the caller's dirty list (residency was lost
        with the workers), every later one honors it."""
        if self._degrade_pending_full:
            self._degrade_pending_full = False
            return None
        return dirty

    def _solve_local(self, arrays, statics, dirty, mode_label):
        from ..parallel.mesh import _pick_devices, dispatch_mesh
        ndev = len(_pick_devices())
        out = dispatch_mesh(arrays, n_max=statics["n_max"],
                            E=statics["E"], P=statics["P"], V=0,
                            ndev=ndev, cache=self._local_cache,
                            dirty=self._dirty_for_local(dirty),
                            metrics=self.metrics)
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_distmesh_dispatch_total",
                             labels={"mode": mode_label})
        return {"out": out,
                "fingerprint":
                    self._distmesh().result_fingerprint(out),
                "mode":
                    self._local_cache["last_placement"]["mode"],
                "distributed": False}

    def solve_seeded(self, shape: dict, seed: int, tick: int,
                     dirty=None, want_arrays: bool = False) -> dict:
        """One distributed solve of the deterministic tick workload
        (distmesh.tick_arrays): each worker regenerates its own slab —
        nothing bulk crosses the control wire. Falls back to the local
        mesh (full arrays, one process) when degraded."""
        statics = {k: shape[k] for k in ("n_max", "E", "P")}
        if not self._check():
            arrays, _ = self._distmesh().tick_arrays(shape, seed, tick)
            return self._solve_local(
                arrays, statics, dirty,
                "degraded" if self._degraded else "local")
        try:
            replies = self._broadcast(lambda pid: ({
                "cmd": "solve_seeded", "shape": shape, "seed": seed,
                "tick": tick, "dirty": dirty,
                "want_arrays": want_arrays and pid == 0}, None))
        except Exception:
            arrays, _ = self._distmesh().tick_arrays(shape, seed, tick)
            return self._solve_local(arrays, statics, dirty, "degraded")
        return self._collect(replies, "seeded", want_arrays)

    def solve_frame(self, arrays: dict, statics: dict,
                    dirty=None, want_arrays: bool = False) -> dict:
        """One distributed solve of caller-supplied arrays (the sidecar
        path — the frame already arrived whole over gRPC): slot tables
        are sliced per worker so each process still commits only its
        slab."""
        if not self._check():
            return self._solve_local(
                arrays, statics, dirty,
                "degraded" if self._degraded else "local")
        dm = self._distmesh()
        nproc = self.workers + 1
        dp = self.mesh_info["dp"]
        N = statics["E"] + statics["n_max"]
        Np = ((N + dp - 1) // dp) * dp

        def pad0(a, rows):
            a = np.asarray(a)
            out = np.zeros((rows,) + a.shape[1:], a.dtype)
            out[:a.shape[0]] = a
            return out

        ex_alloc = pad0(arrays["ex_alloc"], Np)
        ex_used0 = pad0(arrays["ex_used0"], Np)
        compat = np.asarray(arrays["ex_compat"])
        ex_compat = np.zeros(compat.shape[:1] + (Np,), compat.dtype)
        ex_compat[:, :compat.shape[1]] = compat
        repl = {k: np.asarray(v) for k, v in arrays.items()
                if k not in ("ex_alloc", "ex_used0", "ex_compat")
                and v is not None}

        def frame_for(pid):
            lo, hi = dm.local_slot_rows(Np, nproc, pid)
            payload = dict(repl)
            payload["ex_alloc"] = ex_alloc[lo:hi]
            payload["ex_used0"] = ex_used0[lo:hi]
            payload["ex_compat"] = ex_compat[:, lo:hi]
            slabs = {
                "ex_alloc": [lo, hi, 0, [Np, ex_alloc.shape[1]]],
                "ex_used0": [lo, hi, 0, [Np, ex_used0.shape[1]]],
                "ex_compat": [lo, hi, 1, [ex_compat.shape[0], Np]],
            }
            msg = {"cmd": "solve_frame", "dirty": dirty,
                   "want_arrays": want_arrays and pid == 0,
                   "slabs": slabs}
            msg.update({k: int(v) for k, v in statics.items()})
            return msg, payload

        try:
            replies = self._broadcast(frame_for)
        except Exception:
            return self._solve_local(arrays, statics, dirty, "degraded")
        return self._collect(replies, "frame", want_arrays)

    def _collect(self, replies, mode_label, want_arrays):
        fps = {r["fingerprint"] for r, _ in replies}
        if len(fps) != 1:
            # processes disagreeing on a replicated output is a
            # correctness emergency, not a retry case
            self.degrade(reason="fingerprint_split")
            raise RuntimeError(
                f"cross-process fingerprint mismatch: {sorted(fps)}")
        r0, arrays0 = replies[0]
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_distmesh_dispatch_total",
                             labels={"mode": mode_label})
        return {"out": arrays0 if want_arrays else None,
                "fingerprint": r0["fingerprint"], "mode": r0["mode"],
                "timing": r0.get("timing", {}),
                "wall_s": r0.get("wall_s"), "distributed": True}

    def solve_batch(self, stack: np.ndarray, kv: dict
                    ) -> Optional[np.ndarray]:
        """Route SolveBatch lanes across the group: contiguous lane
        spans per process, each solved on that worker's local devices,
        reassembled in order. Returns None when the group cannot serve
        (degraded / routing error) — the caller keeps its local path."""
        if not self._check():
            return None
        stack = np.asarray(stack)
        B = stack.shape[0]
        nproc = self.workers + 1
        spans = []
        base, extra = divmod(B, nproc)
        at = 0
        for pid in range(nproc):
            take = base + (1 if pid < extra else 0)
            spans.append((at, at + take))
            at += take

        def batch_for(pid):
            lo, hi = spans[pid]
            if hi == lo:  # empty span still needs a round trip: the
                # broadcast protocol is strict send-all/recv-all
                lo, hi = 0, 1
            return ({"cmd": "solve_batch",
                     "kv": {k: int(v) for k, v in kv.items()}},
                    {"stack": stack[lo:hi]})

        try:
            replies = self._broadcast(batch_for)
        except Exception:
            return None
        parts = []
        for pid, (_, arrays) in enumerate(replies):
            lo, hi = spans[pid]
            parts.append(arrays["out"][:hi - lo])
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_distmesh_dispatch_total",
                             labels={"mode": "batch"})
        return np.concatenate(parts, axis=0)

    def solve_oracle(self, shape: dict, seed: int, tick: int,
                     want_arrays: bool = False) -> dict:
        """The fingerprint baseline, computed in THIS process on one
        device via the shared dispatch (distmesh.oracle_out)."""
        dm = self._distmesh()
        arrays, statics = dm.tick_arrays(shape, seed, tick)
        out = dm.oracle_out(arrays, **statics)
        return {"out": out if want_arrays else None,
                "fingerprint": dm.result_fingerprint(out)}
