"""The seeded canary solve: correctness-gated (re-)admission.

A replica that answers Info is not necessarily a replica that still
SOLVES — a wedged accelerator runtime, a corrupted compile cache, or a
half-rolled build can keep the control plane green while returning
wrong-but-well-formed decisions. The fleet's admission gate closes
that gap with one tiny deterministic solve, byte-compared against the
local CPU oracle (decision identity across arms is the repo-wide wire
invariant, so ANY divergence is disqualifying):

- ``run_canary(client)`` drives the wire path (``solve_buffer``)
  against a live :class:`~..sidecar.client.SolverClient`; used by
  ``FleetMembership.probe`` before a replica re-enters rotation and by
  ``FleetSolver`` before the binding moves onto a peer.
- ``MESH_CANARY_SHAPE``/``CANARY_SEED`` parameterize the mesh-group
  variant (``MeshGroup._canary_group``): the same workload solved
  through a freshly regrouped ``jax.distributed`` mesh, fingerprinted
  against the oracle before the group serves traffic.

The workload is ``distmesh.tick_arrays`` — the deterministic seeded
generator the chaos harnesses already trust — packed through the
production ``pack_inputs1`` arena, so the canary exercises the real
codec, bucketing, and kernel path, not a mock.

Verdicts are three-valued: True (byte-identical — admit), False
(well-formed but divergent — QUARANTINE, see docs/troubleshooting.md),
None (transport/malformed failure — unhealthy, retry later; transport
flakiness is not evidence of wrong decisions).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

#: tiny wire-canary workload: big enough to exercise slot/type/zone
#: packing, small enough that its one-time compile is negligible
CANARY_SHAPE = dict(G=2, T=5, n_max=8, E=4, P=1, Z=2, C=2, D=4,
                    pods_per_group=3)
#: the mesh-group variant pads its slot axis over dp ranks, so give it
#: a slightly wider one than the wire canary
MESH_CANARY_SHAPE = dict(G=2, T=5, n_max=16, E=4, P=1, Z=2, C=2, D=4,
                         pods_per_group=3)
CANARY_SEED = 1303

_cache: dict = {}


def canary_request() -> Tuple[np.ndarray, dict]:
    """The packed canary arena + its statics, built once per process."""
    if "req" not in _cache:
        from ..ops.hostpack import pack_inputs1
        from ..parallel.distmesh import tick_arrays
        s = CANARY_SHAPE
        arrays, _ = tick_arrays(s, CANARY_SEED, 0)
        dims = {k: int(s[k]) for k in ("T", "D", "Z", "C", "G", "E",
                                       "P")}
        buf = np.asarray(pack_inputs1(
            {k: np.asarray(v) for k, v in arrays.items()}, **dims))
        _cache["req"] = (buf, dict(dims, n_max=int(s["n_max"]), K=0,
                                   V=0, M=0, F=1))
    return _cache["req"]


def expected_rows() -> np.ndarray:
    """The local oracle's answer to the canary, built once per
    process — the byte baseline every admitted replica must match."""
    if "want" not in _cache:
        from ..ops.ffd_jax import solve_scan_packed1
        buf, st = canary_request()
        kv = {k: st[k] for k in ("T", "D", "Z", "C", "G", "E", "P",
                                 "n_max")}
        _cache["want"] = np.asarray(solve_scan_packed1(buf, **kv))
    return _cache["want"]


def run_canary(client) -> Optional[bool]:
    """One canary solve over the wire. True = byte-identical to the
    oracle; False = well-formed but divergent (quarantine the
    replica); None = transport or malformed-reply failure (unhealthy,
    not evidence of wrong decisions)."""
    buf, st = canary_request()
    want = expected_rows()
    try:
        got = np.asarray(client.solve_buffer(buf, dict(st)))
    except Exception:
        return None
    if got.shape != want.shape or got.dtype != want.dtype:
        return False
    return bool(got.tobytes() == want.tobytes())
