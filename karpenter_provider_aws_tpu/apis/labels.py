"""Well-known scheduling labels.

Mirrors the label universe the reference registers into the core scheduler:
core well-known labels (kubernetes.io/*, karpenter.sh/*) plus the 21 AWS
labels registered at pkg/apis/v1/labels.go:31-54, restricted-label patterns
(labels.go:56-77), and extended resource names (labels.go:91-98).
"""

from __future__ import annotations

import re

# --- core (sigs.k8s.io/karpenter + kubernetes) -----------------------------
ARCH = "kubernetes.io/arch"
OS = "kubernetes.io/os"
INSTANCE_TYPE = "node.kubernetes.io/instance-type"
ZONE = "topology.kubernetes.io/zone"
REGION = "topology.kubernetes.io/region"
HOSTNAME = "kubernetes.io/hostname"
CAPACITY_TYPE = "karpenter.sh/capacity-type"
NODEPOOL = "karpenter.sh/nodepool"
NODE_INITIALIZED = "karpenter.sh/initialized"
NODE_REGISTERED = "karpenter.sh/registered"
DO_NOT_DISRUPT_ANNOTATION = "karpenter.sh/do-not-disrupt"
NODEPOOL_HASH_ANNOTATION = "karpenter.sh/nodepool-hash"
NODEPOOL_HASH_VERSION_ANNOTATION = "karpenter.sh/nodepool-hash-version"
#: bumped whenever NodePool.hash() gains/loses fields (v4: added
#: terminationGracePeriod) — the hash controller restamps old-version
#: claims so the computation change itself never reads as drift
NODEPOOL_HASH_VERSION = "v4"

#: deprecated -> canonical well-known labels (core scheduling's
#: NormalizedLabels; the reference supports selecting on the beta names)
NORMALIZED_LABELS = {
    "beta.kubernetes.io/arch": ARCH,
    "beta.kubernetes.io/os": OS,
    "beta.kubernetes.io/instance-type": INSTANCE_TYPE,
    "failure-domain.beta.kubernetes.io/zone": ZONE,
    "failure-domain.beta.kubernetes.io/region": REGION,
    "topology.ebs.csi.aws.com/zone": ZONE,
}

CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_ON_DEMAND = "on-demand"
CAPACITY_TYPE_RESERVED = "reserved"

ARCH_AMD64 = "amd64"
ARCH_ARM64 = "arm64"
OS_LINUX = "linux"
OS_WINDOWS = "windows"

WINDOWS_BUILD = "node.kubernetes.io/windows-build"
#: ami family -> windows build version (labels.go:89-90)
WINDOWS_BUILDS = {"windows2019": "10.0.17763", "windows2022": "10.0.20348"}

# --- AWS provider labels (pkg/apis/v1/labels.go:31-54) ---------------------
_G = "karpenter.k8s.aws"
INSTANCE_HYPERVISOR = f"{_G}/instance-hypervisor"
INSTANCE_ENCRYPTION_IN_TRANSIT = f"{_G}/instance-encryption-in-transit-supported"
INSTANCE_CATEGORY = f"{_G}/instance-category"
INSTANCE_FAMILY = f"{_G}/instance-family"
INSTANCE_GENERATION = f"{_G}/instance-generation"
INSTANCE_LOCAL_NVME = f"{_G}/instance-local-nvme"
INSTANCE_SIZE = f"{_G}/instance-size"
INSTANCE_CPU = f"{_G}/instance-cpu"
INSTANCE_CPU_MANUFACTURER = f"{_G}/instance-cpu-manufacturer"
INSTANCE_CPU_SUSTAINED_CLOCK = f"{_G}/instance-cpu-sustained-clock-speed-mhz"
INSTANCE_MEMORY = f"{_G}/instance-memory"
INSTANCE_EBS_BANDWIDTH = f"{_G}/instance-ebs-bandwidth"
INSTANCE_NETWORK_BANDWIDTH = f"{_G}/instance-network-bandwidth"
INSTANCE_GPU_NAME = f"{_G}/instance-gpu-name"
INSTANCE_GPU_MANUFACTURER = f"{_G}/instance-gpu-manufacturer"
INSTANCE_GPU_COUNT = f"{_G}/instance-gpu-count"
INSTANCE_GPU_MEMORY = f"{_G}/instance-gpu-memory"
INSTANCE_ACCELERATOR_NAME = f"{_G}/instance-accelerator-name"
INSTANCE_ACCELERATOR_MANUFACTURER = f"{_G}/instance-accelerator-manufacturer"
INSTANCE_ACCELERATOR_COUNT = f"{_G}/instance-accelerator-count"
ZONE_ID = "topology.k8s.aws/zone-id"

EC2NODECLASS_LABEL = f"{_G}/ec2nodeclass"
EC2NODECLASS_HASH_ANNOTATION = f"{_G}/ec2nodeclass-hash"
EC2NODECLASS_HASH_VERSION_ANNOTATION = f"{_G}/ec2nodeclass-hash-version"
EC2NODECLASS_HASH_VERSION = "v4"  # pkg/apis/v1/ec2nodeclass.go (v4)

#: the allowlisted karpenter.k8s.aws requirement keys
#: (karpenter.sh_nodepools.yaml:282-283 CEL rule)
AWS_REQUIREMENT_LABELS = frozenset({
    EC2NODECLASS_LABEL, INSTANCE_ENCRYPTION_IN_TRANSIT, INSTANCE_CATEGORY,
    INSTANCE_HYPERVISOR, INSTANCE_FAMILY, INSTANCE_GENERATION,
    INSTANCE_LOCAL_NVME, INSTANCE_SIZE, INSTANCE_CPU,
    INSTANCE_CPU_MANUFACTURER, INSTANCE_CPU_SUSTAINED_CLOCK,
    INSTANCE_MEMORY, INSTANCE_EBS_BANDWIDTH, INSTANCE_NETWORK_BANDWIDTH,
    INSTANCE_GPU_NAME, INSTANCE_GPU_MANUFACTURER, INSTANCE_GPU_COUNT,
    INSTANCE_GPU_MEMORY, INSTANCE_ACCELERATOR_NAME,
    INSTANCE_ACCELERATOR_MANUFACTURER, INSTANCE_ACCELERATOR_COUNT,
})


#: Labels whose values are integers, supporting Gt/Lt requirement operators.
NUMERIC_LABELS = frozenset({
    INSTANCE_CPU, INSTANCE_MEMORY, INSTANCE_GPU_COUNT, INSTANCE_GPU_MEMORY,
    INSTANCE_ACCELERATOR_COUNT, INSTANCE_GENERATION, INSTANCE_EBS_BANDWIDTH,
    INSTANCE_NETWORK_BANDWIDTH, INSTANCE_LOCAL_NVME,
    INSTANCE_CPU_SUSTAINED_CLOCK,
})

#: The full well-known set: pods may constrain these even when a nodepool
#: leaves them undefined (the instance types define them).
WELL_KNOWN_LABELS = frozenset({
    ARCH, OS, INSTANCE_TYPE, ZONE, REGION, CAPACITY_TYPE, NODEPOOL,
    HOSTNAME, ZONE_ID, WINDOWS_BUILD,
    INSTANCE_HYPERVISOR, INSTANCE_ENCRYPTION_IN_TRANSIT, INSTANCE_CATEGORY,
    INSTANCE_FAMILY, INSTANCE_GENERATION, INSTANCE_LOCAL_NVME, INSTANCE_SIZE,
    INSTANCE_CPU, INSTANCE_CPU_MANUFACTURER, INSTANCE_CPU_SUSTAINED_CLOCK,
    INSTANCE_MEMORY, INSTANCE_EBS_BANDWIDTH, INSTANCE_NETWORK_BANDWIDTH,
    INSTANCE_GPU_NAME, INSTANCE_GPU_MANUFACTURER, INSTANCE_GPU_COUNT,
    INSTANCE_GPU_MEMORY, INSTANCE_ACCELERATOR_NAME,
    INSTANCE_ACCELERATOR_MANUFACTURER, INSTANCE_ACCELERATOR_COUNT,
})

# --- restricted tags/labels (labels.go:56-77) ------------------------------
RESTRICTED_TAG_PATTERNS = (
    re.compile(r"^karpenter\.sh/nodepool$"),
    re.compile(r"^karpenter\.sh/nodeclaim$"),
    re.compile(r"^kubernetes\.io/cluster/[0-9A-Za-z][A-Za-z0-9\-_]*$"),
    re.compile(r"^karpenter\.k8s\.aws/ec2nodeclass$"),
    re.compile(r"^eks:eks-cluster-name$"),
)

RESTRICTED_LABEL_DOMAINS = ("kubernetes.io", "k8s.io", "karpenter.sh")
#: subdomains users MAY label under despite the restricted domains above
ALLOWED_LABEL_DOMAINS = (
    "kops.k8s.io", "node.kubernetes.io", "node-restriction.kubernetes.io",
    "karpenter.k8s.aws", "topology.k8s.aws",
)


def is_restricted_label(key: str) -> bool:
    """True if users may not set this label on a NodePool template."""
    if key in WELL_KNOWN_LABELS:
        return False
    domain = key.split("/", 1)[0] if "/" in key else ""
    for allowed in ALLOWED_LABEL_DOMAINS:
        if domain == allowed or domain.endswith("." + allowed):
            return False
    for restricted in RESTRICTED_LABEL_DOMAINS:
        if domain == restricted or domain.endswith("." + restricted):
            return True
    return False


def is_restricted_tag(key: str) -> bool:
    """True if users may not set this cloud tag (cloudprovider.go:232-250)."""
    return any(p.match(key) for p in RESTRICTED_TAG_PATTERNS)
