"""Resource quantities and resource-list arithmetic.

Everything is fixed-point integers from the moment of parsing:

- ``cpu``               millicores (1 core == 1000)
- ``memory``            bytes
- ``ephemeral-storage`` bytes
- everything else       integer counts (pods, nvidia.com/gpu,
                        aws.amazon.com/neuron, vpc.amazonaws.com/pod-eni, ...)

Integer fixed-point is a hard design requirement, not a convenience: the TPU
solver must make decisions bit-identical to the CPU oracle, so no float enters
any quantity or score anywhere in the scheduling path.

Reference parity: resource handling in the reference flows through
k8s resource.Quantity; capacity/overhead construction at
pkg/providers/instancetype/types.go:307-478 (Capacity) and :480-565
(kubeReserved/systemReserved/evictionThreshold), Allocatable() consumed at
pkg/cloudprovider/cloudprovider.go:331.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, Mapping, Optional, Tuple

# Canonical resource names (subset of well-known + AWS extended resources,
# reference: pkg/apis/v1/labels.go:91-98).
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"
NVIDIA_GPU = "nvidia.com/gpu"
AMD_GPU = "amd.com/gpu"
AWS_NEURON = "aws.amazon.com/neuron"
AWS_NEURON_CORE = "aws.amazon.com/neuroncore"
HABANA_GAUDI = "habana.ai/gaudi"
AWS_POD_ENI = "vpc.amazonaws.com/pod-eni"
AWS_PRIVATE_IPV4 = "vpc.amazonaws.com/PrivateIPv4Address"
AWS_EFA = "vpc.amazonaws.com/efa"
#: EBS CSI per-node attachment limit dimension (the core scheduler's
#: CSINode volume-limit accounting; storage suite "respecting volume
#: limits")
ATTACHABLE_VOLUMES = "attachable-volumes-aws-ebs"

# Resources measured in millicores vs bytes vs counts.
_MILLI_RESOURCES = frozenset({CPU})
_BYTE_RESOURCES = frozenset({MEMORY, EPHEMERAL_STORAGE})

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d+)?)(?P<suffix>m|k|M|G|T|P|E|Ki|Mi|Gi|Ti|Pi|Ei)?$"
)

_BINARY = {"Ki": 1024, "Mi": 1024**2, "Gi": 1024**3, "Ti": 1024**4,
           "Pi": 1024**5, "Ei": 1024**6}
_DECIMAL = {"k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12,
            "P": 10**15, "E": 10**18}


def parse_quantity(value: object, resource: str = MEMORY) -> int:
    """Parse a k8s-style quantity into this module's fixed-point integer.

    ``parse_quantity("1", "cpu") == 1000`` (millicores);
    ``parse_quantity("1Gi", "memory") == 1073741824`` (bytes);
    ``parse_quantity("2", "pods") == 2``.
    """
    if isinstance(value, bool):  # bool is an int subclass; reject explicitly
        raise ValueError(f"invalid quantity {value!r}")
    if isinstance(value, int):
        return value * 1000 if resource in _MILLI_RESOURCES else value
    if isinstance(value, float):
        base = value * 1000 if resource in _MILLI_RESOURCES else value
        return int(round(base))
    s = str(value).strip()
    m = _QUANTITY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity {value!r}")
    # Exact integer arithmetic throughout — float's 53-bit mantissa would
    # silently corrupt large byte counts, violating the fixed-point invariant.
    num_str = m.group("num")
    if "." in num_str:
        int_part, frac_part = num_str.split(".", 1)
    else:
        int_part, frac_part = num_str, ""
    whole = int(int_part or "0")
    frac = int(frac_part or "0")
    frac_scale = 10 ** len(frac_part)
    sign = -1 if m.group("sign") == "-" else 1
    suffix = m.group("suffix")
    if suffix == "m":
        # "m" means milli. For cpu this is already our unit; for bytes it is
        # a fractional byte which we round.
        if resource in _MILLI_RESOURCES:
            return sign * (whole + _round_div(frac, frac_scale))
        return sign * _round_div(whole * frac_scale + frac, 1000 * frac_scale)
    mult = 1
    if suffix:
        mult = _BINARY.get(suffix) or _DECIMAL[suffix]
    if resource in _MILLI_RESOURCES:
        mult *= 1000
    return sign * _round_div((whole * frac_scale + frac) * mult, frac_scale)


def _round_div(num: int, den: int) -> int:
    """Round-half-up integer division (matches round() for our quantities)."""
    return (num * 2 + den) // (den * 2)


def format_quantity(amount: int, resource: str) -> str:
    if resource in _MILLI_RESOURCES:
        if amount % 1000 == 0:
            return str(amount // 1000)
        return f"{amount}m"
    if resource in _BYTE_RESOURCES:
        for suffix in ("Ei", "Pi", "Ti", "Gi", "Mi", "Ki"):
            unit = _BINARY[suffix]
            if amount % unit == 0 and amount != 0:
                return f"{amount // unit}{suffix}"
        return str(amount)
    return str(amount)


class Resources(Mapping[str, int]):
    """An immutable resource list with integer quantities.

    Supports +, -, comparison via :meth:`fits`, and max-merge. Missing keys
    read as 0. Zero-valued entries are dropped on construction so equality
    and iteration are canonical.
    """

    __slots__ = ("_q", "_nz")

    def __init__(self, quantities: Optional[Mapping[str, int]] = None, **kw: int):
        q: Dict[str, int] = {}
        for src in (quantities or {}), kw:
            for k, v in src.items():
                if not isinstance(v, int):
                    raise TypeError(
                        f"Resources values must be int (got {k}={v!r}); "
                        "use Resources.parse for quantity strings")
                if v != 0:
                    q[k] = q.get(k, 0) + v
                    if q[k] == 0:
                        del q[k]
        self._q = q

    @classmethod
    def parse(cls, spec: Mapping[str, object]) -> "Resources":
        """Parse a {resource: quantity-string} mapping, e.g.
        ``{"cpu": "100m", "memory": "1Gi", "pods": 1}``. Negative
        quantities are rejected — a negative request/capacity would
        silently corrupt packing arithmetic."""
        out = {}
        for k, v in spec.items():
            q = parse_quantity(v, k)
            if q < 0:
                raise ValueError(f"negative quantity {v!r} for {k}")
            out[k] = q
        return cls(out)

    # Mapping protocol -----------------------------------------------------
    def __getitem__(self, key: str) -> int:
        return self._q.get(key, 0)

    def __iter__(self):
        return iter(self._q)

    def __len__(self) -> int:
        return len(self._q)

    def __contains__(self, key: object) -> bool:
        return key in self._q

    # Arithmetic -----------------------------------------------------------
    def __add__(self, other: "Resources") -> "Resources":
        q = dict(self._q)
        for k, v in other.items():
            q[k] = q.get(k, 0) + v
        return Resources(q)

    def __sub__(self, other: "Resources") -> "Resources":
        q = dict(self._q)
        for k, v in other.items():
            q[k] = q.get(k, 0) - v
        return Resources(q)

    def clamp_nonnegative(self) -> "Resources":
        return Resources({k: v for k, v in self._q.items() if v > 0})

    def fits(self, capacity: "Resources") -> bool:
        """True iff every requested quantity is <= the capacity's quantity."""
        return all(v <= capacity[k] for k, v in self._q.items())

    def exceeds_any(self, other: "Resources") -> bool:
        return not self.fits(other)

    def merge_max(self, other: "Resources") -> "Resources":
        keys = set(self._q) | set(other._q)
        return Resources({k: max(self[k], other[k]) for k in keys})

    def nonzero_keys(self) -> Tuple[str, ...]:
        # memoized: the encoder asks once per group per solve and
        # Resources is immutable (10k calls at the G-axis envelope)
        nz = getattr(self, "_nz", None)
        if nz is None:
            nz = self._nz = tuple(sorted(self._q))
        return nz

    # Identity -------------------------------------------------------------
    def __eq__(self, other: object) -> bool:
        if isinstance(other, Resources):
            return self._q == other._q
        if isinstance(other, Mapping):
            return self._q == {k: v for k, v in other.items() if v != 0}
        return NotImplemented

    def __hash__(self) -> int:
        return hash(tuple(sorted(self._q.items())))

    def __repr__(self) -> str:
        inner = ", ".join(
            f"{k}={format_quantity(v, k)}" for k, v in sorted(self._q.items()))
        return f"Resources({inner})"

    def is_zero(self) -> bool:
        return not self._q


ZERO = Resources()


def sum_resources(items: Iterable[Resources]) -> Resources:
    total = Resources()
    for r in items:
        total = total + r
    return total
