"""The requirements (label-set) algebra.

This is the constraint language the whole scheduler runs on: every NodePool
template requirement, pod nodeSelector / nodeAffinity term, and instance-type
label set compiles into :class:`Requirements`, and scheduling feasibility is
``Requirements.intersects`` / ``compatible``.

Semantics mirror the core library's ``scheduling.Requirements`` exactly as the
reference consumes it (pkg/providers/instancetype/types.go:183-287 constructs
~40 per-type requirements; pkg/cloudprovider/cloudprovider.go:329 checks
``reqs.Compatible(other, AllowUndefinedWellKnownLabels)``;
pkg/providers/instance/instance.go:101 uses
``NewNodeSelectorRequirementsWithMinValues``):

- A :class:`Requirement` is a (possibly complemented) value set with optional
  integer bounds: ``In`` {a,b}, ``NotIn`` ~{a,b}, ``Exists`` ~{},
  ``DoesNotExist`` {}, ``Gt n`` ~{} with lower bound, ``Lt n`` ~{} with upper
  bound; plus ``minValues`` (the NodePool flexibility floor, CRD rule at
  pkg/apis/crds/karpenter.sh_nodepools.yaml:284,327-328).
- Intersection is exact set algebra over the four complement combinations,
  with bounds tightened to the max lower / min upper and, for concrete sets,
  values filtered against bounds.
- ``compatible(incoming, allow_undefined)``: every incoming requirement must
  intersect ours; keys we leave undefined pass only if well-known
  (``allow_undefined``) or the incoming operator is satisfied by label
  absence (NotIn / DoesNotExist — k8s nodeAffinity semantics).

The TPU encoding in ``models/encoding.py`` lowers this algebra to bitmask
tensors; this module is the semantic source of truth it is tested against.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, Iterable, Iterator, List, Mapping, Optional, Sequence, Tuple

from . import labels as L

# Operators (k8s NodeSelectorOperator)
IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"

_OPERATORS = (IN, NOT_IN, EXISTS, DOES_NOT_EXIST, GT, LT)


def _as_int(value: str) -> Optional[int]:
    try:
        return int(value)
    except (TypeError, ValueError):
        return None


@dataclass(frozen=True)
class Requirement:
    """One key's constraint: a (complemented) value set plus integer bounds.

    ``complement=False`` means "value must be in ``values``";
    ``complement=True`` means "value must NOT be in ``values``" (and must
    satisfy the bounds, which only numeric strings can).
    """

    key: str
    complement: bool = False
    values: FrozenSet[str] = frozenset()
    greater_than: Optional[int] = None  # exclusive lower bound
    less_than: Optional[int] = None     # exclusive upper bound
    min_values: Optional[int] = None
    #: True only for intersection results proven unsatisfiable even by label
    #: absence (e.g. In{a} ∩ In{b}). Distinguishes "empty In" from
    #: DoesNotExist, which absence satisfies — the distinction upstream
    #: karpenter keeps by special-casing NotIn/DoesNotExist operators in
    #: Requirements.Intersects.
    impossible: bool = False

    # -- constructors ------------------------------------------------------
    @staticmethod
    def new(key: str, operator: str, values: Sequence[str] = (),
            min_values: Optional[int] = None) -> "Requirement":
        # deprecated well-known labels select on their canonical form
        # (core scheduling NormalizedLabels)
        from . import labels as _L
        key = _L.NORMALIZED_LABELS.get(key, key)
        values = tuple(str(v) for v in values)
        if operator == IN:
            return Requirement(key, False, frozenset(values), None, None, min_values)
        if operator == NOT_IN:
            return Requirement(key, True, frozenset(values), None, None, min_values)
        if operator == EXISTS:
            return Requirement(key, True, frozenset(), None, None, min_values)
        if operator == DOES_NOT_EXIST:
            return Requirement(key, False, frozenset(), None, None, min_values)
        if operator == GT:
            if len(values) != 1 or _as_int(values[0]) is None:
                raise ValueError(f"Gt requires one integer value, got {values!r}")
            return Requirement(key, True, frozenset(), _as_int(values[0]), None, min_values)
        if operator == LT:
            if len(values) != 1 or _as_int(values[0]) is None:
                raise ValueError(f"Lt requires one integer value, got {values!r}")
            return Requirement(key, True, frozenset(), None, _as_int(values[0]), min_values)
        raise ValueError(f"unknown operator {operator!r}; expected one of {_OPERATORS}")

    @property
    def operator(self) -> str:
        """Best-effort canonical operator for serialization."""
        if self.greater_than is not None and self.less_than is None and not self.values:
            return GT
        if self.less_than is not None and self.greater_than is None and not self.values:
            return LT
        if self.complement:
            return EXISTS if not self.values and self._unbounded else NOT_IN
        return IN if self.values else DOES_NOT_EXIST

    @property
    def _unbounded(self) -> bool:
        return self.greater_than is None and self.less_than is None

    # -- membership --------------------------------------------------------
    def _in_bounds(self, value: str) -> bool:
        if self._unbounded:
            return True
        n = _as_int(value)
        if n is None:
            return False
        if self.greater_than is not None and n <= self.greater_than:
            return False
        if self.less_than is not None and n >= self.less_than:
            return False
        return True

    def has(self, value: str) -> bool:
        if self.impossible:
            return False
        value = str(value)
        if self.complement:
            return value not in self.values and self._in_bounds(value)
        return value in self.values and self._in_bounds(value)

    def satisfied_by_absence(self) -> bool:
        """Does a node *without* this label satisfy the requirement?

        k8s nodeAffinity: NotIn and DoesNotExist match absent labels;
        In/Exists/Gt/Lt require the label present.
        """
        if self.impossible:
            return False
        if self.complement:
            return self._unbounded and bool(self.values)  # NotIn
        return not self.values  # DoesNotExist

    # -- set algebra -------------------------------------------------------
    def intersection(self, other: "Requirement") -> "Requirement":
        assert self.key == other.key, (self.key, other.key)
        gt = self.greater_than
        if other.greater_than is not None:
            gt = other.greater_than if gt is None else max(gt, other.greater_than)
        lt = self.less_than
        if other.less_than is not None:
            lt = other.less_than if lt is None else min(lt, other.less_than)
        if self.complement and other.complement:
            comp, vals = True, self.values | other.values
        elif self.complement:
            comp, vals = False, other.values - self.values
        elif other.complement:
            comp, vals = False, self.values - other.values
        else:
            comp, vals = False, self.values & other.values
        mv = self.min_values
        if other.min_values is not None:
            mv = other.min_values if mv is None else max(mv, other.min_values)
        r = Requirement(self.key, comp, frozenset(vals), gt, lt, mv)
        if not comp and not r._unbounded:
            r = Requirement(self.key, False,
                            frozenset(v for v in vals if r._in_bounds(v)),
                            gt, lt, mv)
        if r.is_empty() and not (self.satisfied_by_absence()
                                 and other.satisfied_by_absence()):
            # no value works and absence doesn't either: mark the result
            # impossible so it can't masquerade as DoesNotExist
            r = Requirement(self.key, r.complement, r.values, gt, lt, mv,
                            impossible=True)
        if self.impossible or other.impossible:
            r = Requirement(self.key, r.complement, r.values, gt, lt, mv,
                            impossible=True)
        return r

    def unsatisfiable(self) -> bool:
        """True iff neither any value nor label absence satisfies this."""
        return self.impossible or (self.is_empty()
                                   and not self.satisfied_by_absence())

    def is_empty(self) -> bool:
        """True iff no value can satisfy this requirement (absence might
        still — see unsatisfiable())."""
        if not self.complement:
            return not self.values
        # Complement set: infinitely many strings unless both bounds close
        # the numeric range (bounded complements only admit numeric values).
        if self.greater_than is not None and self.less_than is not None:
            lo, hi = self.greater_than + 1, self.less_than - 1
            if lo > hi:
                return True
            count = hi - lo + 1
            excluded = sum(1 for v in self.values
                           if (n := _as_int(v)) is not None and lo <= n <= hi)
            return excluded >= count
        return False

    def intersects(self, other: "Requirement") -> bool:
        """Can some node satisfy both? Mirrors upstream karpenter's
        Requirements.Intersects: an empty value intersection is still
        compatible when BOTH sides are satisfied by label absence
        (NotIn/DoesNotExist)."""
        return not self.intersection(other).unsatisfiable()

    def any_value(self) -> Optional[str]:
        """A deterministic representative value, if one is nameable."""
        if not self.complement:
            for v in sorted(self.values):
                if self._in_bounds(v):
                    return v
            return None
        if self.greater_than is not None or self.less_than is not None:
            lo = (self.greater_than + 1) if self.greater_than is not None else 0
            hi = (self.less_than - 1) if self.less_than is not None else lo + len(self.values) + 1
            for n in range(lo, hi + 1):
                if str(n) not in self.values:
                    return str(n)
            return None
        return None  # unbounded complement: no canonical representative

    def with_min_values(self, min_values: Optional[int]) -> "Requirement":
        return Requirement(self.key, self.complement, self.values,
                           self.greater_than, self.less_than, min_values)

    def __len__(self) -> int:
        if self.complement:
            return 1 << 30  # "infinite"
        return sum(1 for v in self.values if self._in_bounds(v))

    def __repr__(self) -> str:
        op = self.operator
        if op in (GT, LT):
            bound = self.greater_than if op == GT else self.less_than
            return f"{self.key} {op} {bound}"
        if op in (EXISTS, DOES_NOT_EXIST):
            return f"{self.key} {op}"
        return f"{self.key} {op} {sorted(self.values)}"


class Requirements:
    """An immutable conjunction of per-key requirements.

    Constructing from multiple requirements on one key intersects them
    (mirrors core ``NewRequirements``).
    """

    __slots__ = ("_by_key",)

    def __init__(self, reqs: Iterable[Requirement] = ()):
        by_key: Dict[str, Requirement] = {}
        for r in reqs:
            cur = by_key.get(r.key)
            by_key[r.key] = r if cur is None else cur.intersection(r)
        self._by_key = by_key

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_labels(cls, lbls: Mapping[str, str]) -> "Requirements":
        return cls(Requirement.new(k, IN, [v]) for k, v in lbls.items())

    @classmethod
    def from_node_selector(cls, selector: Mapping[str, str]) -> "Requirements":
        return cls.from_labels(selector)

    @classmethod
    def from_terms(cls, terms: Sequence[Mapping[str, object]]) -> "Requirements":
        """Parse k8s-shaped ``[{key, operator, values, minValues?}, ...]``."""
        return cls(
            Requirement.new(
                str(t["key"]), str(t.get("operator", IN)),
                [str(v) for v in t.get("values", []) or []],
                t.get("minValues"))  # type: ignore[arg-type]
            for t in terms)

    # -- accessors ---------------------------------------------------------
    def get(self, key: str) -> Optional[Requirement]:
        return self._by_key.get(key)

    def __getitem__(self, key: str) -> Requirement:
        return self._by_key[key]

    def __contains__(self, key: str) -> bool:
        return key in self._by_key

    def keys(self) -> Tuple[str, ...]:
        return tuple(sorted(self._by_key))

    def __iter__(self) -> Iterator[Requirement]:
        for k in sorted(self._by_key):
            yield self._by_key[k]

    def __len__(self) -> int:
        return len(self._by_key)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Requirements) and self._by_key == other._by_key

    def __hash__(self) -> int:
        return hash(tuple(sorted(self._by_key.items(), key=lambda kv: kv[0])))

    def __repr__(self) -> str:
        return "Requirements(" + ", ".join(repr(r) for r in self) + ")"

    # -- algebra -----------------------------------------------------------
    def add(self, *reqs: Requirement) -> "Requirements":
        return Requirements(list(self._by_key.values()) + list(reqs))

    def union(self, other: "Requirements") -> "Requirements":
        """Conjunction (core ``Add``): same-key requirements intersect."""
        # an empty side changes nothing; Requirements are immutable, so
        # returning the other side is safe — and the decode path unions
        # thousands of empty group-requirement sets per solve
        if not other._by_key:
            return self
        if not self._by_key:
            return other
        return Requirements(list(self._by_key.values()) + list(other._by_key.values()))

    def conflicts(self, other: "Requirements") -> List[str]:
        """Keys defined on BOTH sides whose intersection is empty.

        Empty list => the two requirement sets can coexist on one node.
        (Named ``conflicts`` deliberately: truthy means they canNOT coexist,
        the opposite polarity of ``Requirement.intersects``.)
        """
        conflicts = []
        for key, mine in self._by_key.items():
            theirs = other._by_key.get(key)
            if theirs is not None and not mine.intersects(theirs):
                conflicts.append(key)
        return sorted(conflicts)

    def compatible(self, incoming: "Requirements",
                   allow_undefined: FrozenSet[str] = L.WELL_KNOWN_LABELS,
                   ) -> List[str]:
        """Can a node shaped by *self* satisfy *incoming* (pod) requirements?

        Returns the list of offending keys (empty => compatible). Mirrors
        core ``Requirements.Compatible(other, AllowUndefinedWellKnownLabels)``
        as consumed at pkg/cloudprovider/cloudprovider.go:329.
        """
        offending = []
        for key, req in incoming._by_key.items():
            mine = self._by_key.get(key)
            if mine is not None:
                if not mine.intersects(req):
                    offending.append(key)
            else:
                if key not in allow_undefined and not req.satisfied_by_absence():
                    offending.append(key)
        return sorted(offending)

    def is_compatible(self, incoming: "Requirements",
                      allow_undefined: FrozenSet[str] = L.WELL_KNOWN_LABELS,
                      ) -> bool:
        return not self.compatible(incoming, allow_undefined)

    def satisfied_by_labels(self, lbls: Mapping[str, str]) -> bool:
        """Do concrete node labels satisfy every requirement?"""
        for key, req in self._by_key.items():
            if key in lbls:
                if not req.has(lbls[key]):
                    return False
            elif not req.satisfied_by_absence():
                return False
        return True

    def single_values(self) -> Dict[str, str]:
        """Keys constrained to exactly one value -> that value.

        Used to back-fill NodeClaim labels from the chosen instance type
        (cloudprovider.go:381-400).
        """
        out = {}
        for key, req in self._by_key.items():
            if not req.complement and len(req) == 1:
                out[key] = next(v for v in sorted(req.values) if req._in_bounds(v))
        return out

    def min_values_violations(self, key_cardinality: Mapping[str, int]) -> List[str]:
        """Keys whose minValues floor exceeds the available cardinality."""
        out = []
        for key, req in self._by_key.items():
            if req.min_values is not None:
                if key_cardinality.get(key, 0) < req.min_values:
                    out.append(key)
        return sorted(out)

    def to_terms(self) -> List[Dict[str, object]]:
        out: List[Dict[str, object]] = []
        for req in self:
            term: Dict[str, object] = {"key": req.key, "operator": req.operator}
            if req.operator in (IN, NOT_IN):
                term["values"] = sorted(req.values)
            elif req.operator == GT:
                term["values"] = [str(req.greater_than)]
            elif req.operator == LT:
                term["values"] = [str(req.less_than)]
            if req.min_values is not None:
                term["minValues"] = req.min_values
            out.append(term)
        return out


EMPTY = Requirements()
