from . import labels, requirements, resources
from .objects import (BlockDeviceMapping, Condition, DisruptionBudget,
                      Disruption, EC2NodeClass, KubeletConfiguration,
                      MetadataOptions, Node, NodeClaim, NodeClassRef,
                      NodePool, NodePoolTemplate, ObjectMeta, Pod,
                      PodAffinityTerm, SelectorTerm, Taint, Toleration,
                      TopologySpreadConstraint, stable_hash)
from .requirements import Requirement, Requirements
from .resources import Resources, parse_quantity, sum_resources

__all__ = [
    "labels", "requirements", "resources",
    "Requirement", "Requirements", "Resources", "parse_quantity",
    "sum_resources", "Pod", "Node", "NodeClaim", "NodePool",
    "NodePoolTemplate", "NodeClassRef", "EC2NodeClass", "Taint", "Toleration",
    "TopologySpreadConstraint", "PodAffinityTerm", "DisruptionBudget",
    "Disruption", "SelectorTerm", "MetadataOptions", "BlockDeviceMapping",
    "KubeletConfiguration", "ObjectMeta", "Condition", "stable_hash",
]
