"""Kubernetes-shaped object model: the user API surface.

NodePool / NodeClaim / EC2NodeClass are the *entire* user API of the
reference (SURVEY §2.2); plus the workload-side objects the scheduler
consumes (Pod with scheduling constraints, Node). These are plain dataclasses
— the in-memory kube API in ``fake/kube.py`` stores and watches them.

Parity cites: EC2NodeClassSpec pkg/apis/v1/ec2nodeclass.go:30 (selector
terms :141,156,174, KubeletConfiguration :212, MetadataOptions :278,
BlockDeviceMapping :326, alias parsing :494-548); NodePool disruption policy
pkg/apis/crds/karpenter.sh_nodepools.yaml:78-141; NodeClaim reconstruction
pkg/cloudprovider/cloudprovider.go:352-378.
"""

from __future__ import annotations

import hashlib
import itertools
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from . import labels as L
from .requirements import IN, Requirement, Requirements
from .resources import ATTACHABLE_VOLUMES, Resources

_uid_counter = itertools.count(1)


def scaled_percent(pct: int, total: int, up: bool) -> int:
    """Exact integer percent scaling (k8s GetScaledValueFromIntOrPercent
    semantics — float math mis-rounds cases like 29% of 100). ``up``
    picks the ceiling (minAvailable, maxUnavailable and budget nodes%
    all resolve with roundUp=true in kube-controller-manager and core
    karpenter), else the floor."""
    return -((-pct * total) // 100) if up else (pct * total) // 100


def _new_uid(prefix: str) -> str:
    return f"{prefix}-{next(_uid_counter):08x}"


@dataclass
class Condition:
    type: str
    status: str  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition: float = 0.0


@dataclass
class ObjectMeta:
    name: str
    namespace: str = ""
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None
    finalizers: List[str] = field(default_factory=list)
    resource_version: int = 0
    owner_refs: List[str] = field(default_factory=list)  # "Kind/ns/name/uid" refs (ephemeral PVCs; UID-matched like k8s ownerRefs)

    def __post_init__(self):
        if not self.uid:
            self.uid = _new_uid(self.name or "obj")


class KubeObject:
    """Base for objects stored in the (fake) kube API."""
    kind: str = "Object"
    metadata: ObjectMeta

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def uid(self) -> str:
        return self.metadata.uid

    def key(self) -> Tuple[str, str, str]:
        return (self.kind, self.metadata.namespace, self.metadata.name)


# ---------------------------------------------------------------------------
# Taints / tolerations (k8s semantics)
# ---------------------------------------------------------------------------

TAINT_NO_SCHEDULE = "NoSchedule"
TAINT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_NO_EXECUTE = "NoExecute"

#: Karpenter's own taints, tolerated implicitly by nothing — the unregistered
#: taint gates pods until the node initializes (core semantics).
UNREGISTERED_TAINT = "karpenter.sh/unregistered"
DISRUPTED_TAINT = "karpenter.sh/disrupted"


@dataclass(frozen=True)
class Taint:
    key: str
    effect: str = TAINT_NO_SCHEDULE
    value: str = ""

    def tolerated_by(self, tolerations: Sequence["Toleration"]) -> bool:
        if self.effect == TAINT_PREFER_NO_SCHEDULE:
            return True  # preference, not a hard constraint
        return any(t.tolerates(self) for t in tolerations)


@dataclass(frozen=True)
class Toleration:
    key: str = ""               # empty key + Exists tolerates everything
    operator: str = "Equal"     # "Equal" | "Exists"
    value: str = ""
    effect: str = ""            # empty matches all effects

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if not self.key:
            return self.operator == "Exists"
        if self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


# ---------------------------------------------------------------------------
# Pod (the scheduler's input)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str            # e.g. topology.kubernetes.io/zone, hostname
    when_unsatisfiable: str = "DoNotSchedule"  # or ScheduleAnyway
    # label selector is simplified to "same spread group key" — pods carry a
    # precomputed group identity (the common case: selector == own labels).
    group: str = ""


@dataclass(frozen=True)
class PodAffinityTerm:
    topology_key: str
    group: str                   # label-selector group identity
    anti: bool = False           # True => anti-affinity
    required: bool = True


#: per-pod memo key for the preference-chain length; owned here (the
#: apis layer) so solver/preferences.py can import it without a cycle
PREF_COUNT_MEMO = "_pref_count"


def invalidate_scheduling_caches(pod: "Pod") -> None:
    """Drop every memo derived from a pod's scheduling constraints.
    THE authoritative attribute list — both constraint-mutation sites
    (volume-topology application in Pod.apply_volume_constraints and
    preference hardening in solver/preferences.py) call this."""
    pod.__dict__.pop("_reqs_cache", None)
    pod.__dict__.pop("_eff_requests", None)
    for stale in ("_sig_id", "_sig_cache", "_sig_digest", "_hardened",
                  PREF_COUNT_MEMO):
        pod.__dict__.pop(stale, None)


class Pod(KubeObject):
    kind = "Pod"

    def __init__(self, name: str, namespace: str = "default",
                 requests: Optional[Resources] = None,
                 node_selector: Optional[Mapping[str, str]] = None,
                 required_affinity_terms: Sequence[Mapping[str, Any]] = (),
                 tolerations: Sequence[Toleration] = (),
                 topology_spread: Sequence[TopologySpreadConstraint] = (),
                 pod_affinity: Sequence[PodAffinityTerm] = (),
                 labels: Optional[Dict[str, str]] = None,
                 node_name: str = "",
                 phase: str = "Pending",
                 owner_kind: str = "",
                 scheduling_group: str = "",
                 volume_claims: Sequence[str] = (),
                 ephemeral_volumes: Sequence[Tuple[str, str]] = (),
                 priority_class_name: str = "",
                 termination_grace_period_seconds: float = 30.0,
                 init_requests: Optional[Resources] = None):
        # sort identity, set eagerly: canonical grouping sorts millions
        # of pods by this key per solve — an instance attribute lets the
        # hot sort use operator.attrgetter (C speed) instead of a
        # memoizing helper function
        self._nskey = (namespace, name)
        self._full_name = f"{namespace}/{name}"
        self.metadata = ObjectMeta(name=name, namespace=namespace,
                                   labels=dict(labels or {}))
        self.requests = requests if requests is not None else Resources()
        self.node_selector = dict(node_selector or {})
        self.required_affinity_terms = list(required_affinity_terms)
        self.tolerations = list(tolerations)
        self.topology_spread = list(topology_spread)
        self.pod_affinity = list(pod_affinity)
        self.node_name = node_name
        self.phase = phase
        self.owner_kind = owner_kind
        self.scheduling_group = scheduling_group  # identity for spread/affinity
        #: PVC names this pod mounts (spec.volumes[].persistentVolumeClaim)
        self.volume_claims = list(volume_claims)
        #: generic ephemeral volumes (spec.volumes[].ephemeral): (volume
        #: name, storage class). The PVC is OWNED by the pod and named
        #: `<pod>-<volume>` (the k8s generic-ephemeral convention); the
        #: kubelet creates it at bind time, and the provisioner's volume
        #: resolution counts it toward attachment slots and applies its
        #: class's allowed topologies before any PVC object exists.
        self.ephemeral_volumes = [tuple(e) for e in ephemeral_volumes]
        #: system-node-critical / system-cluster-critical pods drain
        #: LAST (the terminator's drain order)
        self.priority_class_name = priority_class_name
        #: k8s spec.terminationGracePeriodSeconds (default 30): on a
        #: node with a terminationGracePeriod, a blocked pod is
        #: force-deleted early enough to receive this full window
        #: (karpenter.sh_nodepools.yaml:416)
        self.termination_grace_period_seconds = \
            termination_grace_period_seconds
        #: largest single init container's requests; the k8s effective
        #: pod request is max(init, sum(containers)) element-wise —
        #: a heavy init step sizes the node even if steady state is
        #: small (the reference's InitContainers right-sizing E2E)
        self.init_requests = init_requests
        #: resolved scheduling priority (spec.priority). Filled by
        #: resolve_pod_priorities from the cluster's PriorityClass
        #: objects; stays 0 when no PriorityClass exists so priority-
        #: free clusters keep byte-identical signatures and solver
        #: fingerprints (the feature is invisible until opted into).
        self.priority = 0
        #: resolved preemptionPolicy ("" = PreemptLowerPriority default;
        #: "Never" pods never trigger eviction of others)
        self.preemption_policy = ""

    def apply_volume_constraints(self, reqs: "Requirements",
                                 n_volumes: int) -> None:
        """Install volume-topology-derived requirements + the EBS
        attachment count before a solve (the provisioner's
        volume-topology resolution, core volumetopology.go). Invalidate
        the scheduling memos so the new constraints take effect; no-op
        when nothing changed (steady-state cycles keep their caches)."""
        if getattr(self, "_volume_count", None) == n_volumes \
                and getattr(self, "_volume_reqs", None) == reqs:
            return
        self._volume_reqs = reqs
        self._volume_count = n_volumes
        invalidate_scheduling_caches(self)

    def scheduling_requirements(self) -> Requirements:
        """nodeSelector ∧ required nodeAffinity terms -> Requirements.
        Memoized — pods are not mutated while a solve is in flight."""
        cached = getattr(self, "_reqs_cache", None)
        if cached is None:
            cached = Requirements.from_labels(self.node_selector)
            if self.required_affinity_terms:
                cached = cached.union(
                    Requirements.from_terms(self.required_affinity_terms))
            vol = getattr(self, "_volume_reqs", None)
            if vol is not None:
                cached = cached.union(vol)
            self._reqs_cache = cached
        return cached

    def full_name(self) -> str:
        """namespace/name — the identity used in solver decisions (pod
        names alone collide across namespaces). Set eagerly in __init__;
        hot paths read the attribute directly."""
        return self._full_name

    def effective_requests(self) -> Resources:
        """max(init, app) requests + the implicit 1-pod slot.
        Memoized (hot path)."""
        cached = getattr(self, "_eff_requests", None)
        if cached is None:
            base = self.requests
            if self.init_requests is not None:
                base = base.merge_max(self.init_requests)
            cached = base + Resources({"pods": 1}) \
                if base["pods"] == 0 else base
            nvol = getattr(self, "_volume_count", 0)
            if nvol:
                cached = cached + Resources({ATTACHABLE_VOLUMES: nvol})
            self._eff_requests = cached
        return cached

    def is_pending_unscheduled(self) -> bool:
        return self.phase == "Pending" and not self.node_name \
            and self.metadata.deletion_timestamp is None


# ---------------------------------------------------------------------------
# PriorityClass
# ---------------------------------------------------------------------------

#: the two built-in system classes; their pods drain LAST (lifecycle
#: drain ordering) and are never preemption victims. THE membership
#: list — lifecycle re-exports it so both consumers share one tuple.
CRITICAL_PRIORITY_CLASSES = ("system-cluster-critical",
                             "system-node-critical")


def is_critical(pod: "Pod") -> bool:
    """Shared critical-pod gate: lifecycle drain ordering and the
    preemption never-victim filter both route through here so the two
    paths cannot drift (satellite contract)."""
    return pod.priority_class_name in CRITICAL_PRIORITY_CLASSES


class PriorityClass(KubeObject):
    """scheduling.k8s.io/v1 PriorityClass: a named integer priority.

    Only explicitly-created objects participate — there are no implicit
    built-in values, so a cluster with zero PriorityClass objects
    resolves every pod to priority 0 and the whole priority axis stays
    wire-invisible (Q=0, identical fingerprints)."""
    kind = "PriorityClass"

    def __init__(self, name: str, value: int,
                 global_default: bool = False,
                 preemption_policy: str = "PreemptLowerPriority"):
        self.metadata = ObjectMeta(name=name, namespace="")
        self.value = int(value)
        self.global_default = bool(global_default)
        #: "PreemptLowerPriority" (default) or "Never"
        self.preemption_policy = preemption_policy


def resolve_pod_priorities(pods: Sequence["Pod"],
                           priority_classes: Sequence[PriorityClass]) \
        -> None:
    """Resolve each pod's spec.priority from the PriorityClass table
    (admission-controller semantics: named class wins, else the
    globalDefault class, else 0). Mutates pod.priority /
    pod.preemption_policy in place and invalidates scheduling memos on
    change — priority is part of the group signature once nonzero.

    With an empty table this is a no-op for already-zero pods (the
    common path), keeping priority-free clusters cache-warm and
    fingerprint-identical."""
    by_name = {pc.metadata.name: pc for pc in priority_classes}
    default = None
    for pc in priority_classes:
        if pc.global_default and (default is None
                                  or pc.value > default.value):
            default = pc
    for pod in pods:
        pc = by_name.get(pod.priority_class_name) or default
        prio = pc.value if pc is not None else 0
        policy = "" if pc is None \
            else ("Never" if pc.preemption_policy == "Never" else "")
        if pod.priority != prio or pod.preemption_policy != policy:
            pod.priority = prio
            pod.preemption_policy = policy
            invalidate_scheduling_caches(pod)


# ---------------------------------------------------------------------------
# NodePool
# ---------------------------------------------------------------------------

@dataclass
class DisruptionBudget:
    nodes: str = "10%"           # count or percentage
    reasons: Optional[List[str]] = None  # None => all reasons
    #: upstream cronjob syntax (plus @-shortcuts), naive UTC; paired
    #: with duration by validation. None => always active
    schedule: Optional[str] = None
    #: seconds; the CRD's "8h"/"1h30m" string form is normalized to
    #: seconds at construction (__post_init__)
    duration: Optional[float] = None

    def __post_init__(self):
        if isinstance(self.duration, str):
            from ..utils.cron import parse_duration
            self.duration = parse_duration(self.duration)

    def allows(self, reason: str) -> bool:
        return self.reasons is None or reason in self.reasons

    def active(self, now: float) -> bool:
        """Schedule window: active from each schedule firing until
        firing + duration (core budget semantics; the CRD documents the
        syntax at karpenter.sh_nodepools.yaml:126-133)."""
        if self.schedule is None:
            return True
        from ..utils.cron import Cron
        cron = getattr(self, "_cron", None)
        if cron is None or getattr(self, "_cron_src", None) != self.schedule:
            cron = Cron(self.schedule)
            self._cron = cron
            self._cron_src = self.schedule
        fire = cron.most_recent_fire(now)
        return fire is not None and self.duration is not None \
            and now < fire + self.duration

    def max_disruptions(self, total_nodes: int) -> int:
        s = self.nodes.strip()
        if s.endswith("%"):
            # ceiling: the default 10% budget must not freeze small
            # clusters (a 2-node pool still allows 1 disruption)
            return scaled_percent(int(s[:-1]), total_nodes, up=True)
        return int(s)


@dataclass
class Disruption:
    consolidation_policy: str = "WhenEmptyOrUnderutilized"  # | WhenEmpty
    consolidate_after: float = 0.0   # seconds; 0 => immediately
    budgets: List[DisruptionBudget] = field(default_factory=lambda: [DisruptionBudget()])


@dataclass
class NodeClassRef:
    name: str
    kind: str = "EC2NodeClass"
    group: str = "karpenter.k8s.aws"


@dataclass
class NodePoolTemplate:
    node_class_ref: NodeClassRef
    requirements: Requirements = field(default_factory=Requirements)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    startup_taints: List[Taint] = field(default_factory=list)
    expire_after: Optional[float] = None  # seconds
    termination_grace_period: Optional[float] = None


class NodePool(KubeObject):
    kind = "NodePool"

    def __init__(self, name: str,
                 template: NodePoolTemplate,
                 disruption: Optional[Disruption] = None,
                 limits: Optional[Resources] = None,
                 weight: int = 0,
                 labels: Optional[Dict[str, str]] = None):
        self.metadata = ObjectMeta(name=name, labels=dict(labels or {}))
        self.template = template
        self.disruption = disruption or Disruption()
        self.limits = limits  # None => unlimited
        self.weight = weight
        self.status_resources = Resources()  # aggregated in-use resources

    def scheduling_requirements(self) -> Requirements:
        """Template requirements ∧ template labels ∧ the nodepool label."""
        reqs = self.template.requirements
        reqs = reqs.union(Requirements.from_labels(self.template.labels))
        return reqs.add(Requirement.new(L.NODEPOOL, IN, [self.name]))

    def hash(self) -> str:
        return stable_hash({
            "labels": self.template.labels,
            "annotations": self.template.annotations,
            "taints": [(t.key, t.effect, t.value) for t in self.template.taints],
            "startupTaints": [(t.key, t.effect, t.value) for t in self.template.startup_taints],
            "expireAfter": self.template.expire_after,
            # in the static drift hash upstream too: retuning a pool's
            # terminationGracePeriod must reach existing claims (e.g. to
            # unpin a node held by a do-not-disrupt pod) via drift
            "terminationGracePeriod": self.template.termination_grace_period,
        })


# ---------------------------------------------------------------------------
# NodeClaim
# ---------------------------------------------------------------------------

class NodeClaim(KubeObject):
    kind = "NodeClaim"

    def __init__(self, name: str,
                 requirements: Requirements,
                 node_class_ref: NodeClassRef,
                 resources_requested: Resources = Resources(),
                 taints: Sequence[Taint] = (),
                 startup_taints: Sequence[Taint] = (),
                 labels: Optional[Dict[str, str]] = None,
                 annotations: Optional[Dict[str, str]] = None,
                 expire_after: Optional[float] = None,
                 termination_grace_period: Optional[float] = None):
        self.metadata = ObjectMeta(name=name, labels=dict(labels or {}),
                                   annotations=dict(annotations or {}))
        self.requirements = requirements
        self.node_class_ref = node_class_ref
        self.resources_requested = resources_requested
        self.taints = list(taints)
        self.startup_taints = list(startup_taints)
        self.expire_after = expire_after
        #: seconds the terminator waits before force-draining (bypassing
        #: do-not-disrupt); None = wait indefinitely
        #: (karpenter.sh_nodepools.yaml:407-416)
        self.termination_grace_period = termination_grace_period
        # status
        self.provider_id: str = ""
        self.image_id: str = ""
        self.capacity: Resources = Resources()
        self.allocatable: Resources = Resources()
        self.node_name: str = ""
        self.conditions: Dict[str, Condition] = {}
        self.last_pod_event: float = 0.0

    @property
    def nodepool(self) -> Optional[str]:
        return self.metadata.labels.get(L.NODEPOOL)

    @property
    def instance_type_names(self) -> List[str]:
        """Candidate instance types the solver planned for this claim
        (cheapest-first; the launch path truncates to 60)."""
        return list(getattr(self, "instance_type_options", []))

    def set_condition(self, ctype: str, status: str, reason: str = "",
                      message: str = "", now: float = 0.0) -> None:
        self.conditions[ctype] = Condition(ctype, status, reason, message, now)

    def condition_is(self, ctype: str, status: str = "True") -> bool:
        c = self.conditions.get(ctype)
        return c is not None and c.status == status

    @property
    def launched(self) -> bool:
        return self.condition_is("Launched")

    @property
    def registered(self) -> bool:
        return self.condition_is("Registered")

    @property
    def initialized(self) -> bool:
        return self.condition_is("Initialized")


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------

class PodDisruptionBudget(KubeObject):
    """policy/v1 PodDisruptionBudget — the eviction gate Karpenter
    honors in disruption decisions and during drain (a blocked PDB
    holds a node like do-not-disrupt does; the claim's
    terminationGracePeriod bypasses it, karpenter.sh_nodepools.yaml:411).
    Exactly one of min_available / max_unavailable is set; values are
    counts or percentages ("50%"). k8s rounding: the disruption
    controller resolves BOTH minAvailable % and maxUnavailable % with
    GetScaledValueFromIntOrPercent(roundUp=true)."""

    kind = "PodDisruptionBudget"

    def __init__(self, name: str, selector: Mapping[str, str],
                 min_available: "int | str | None" = None,
                 max_unavailable: "int | str | None" = None,
                 namespace: str = "default"):
        if (min_available is None) == (max_unavailable is None):
            raise ValueError(
                "exactly one of minAvailable/maxUnavailable is required")
        self.metadata = ObjectMeta(name=name, namespace=namespace)
        self.selector = dict(selector)
        self.min_available = min_available
        self.max_unavailable = max_unavailable

    def matches(self, pod) -> bool:
        if pod.metadata.namespace != self.metadata.namespace:
            return False
        labels = pod.metadata.labels
        return all(labels.get(k) == v for k, v in self.selector.items())

    def disruptions_allowed(self, matching, healthy: int) -> int:
        """How many more matching pods may be evicted right now."""
        total = len(matching)
        if self.max_unavailable is not None:
            cap = self._resolve(self.max_unavailable, total, up=True)
            return max(0, cap - (total - healthy))
        floor = self._resolve(self.min_available, total, up=True)
        return max(0, healthy - floor)

    @staticmethod
    def _resolve(v, total: int, up: bool) -> int:
        if isinstance(v, str) and v.strip().endswith("%"):
            return scaled_percent(int(v.strip()[:-1]), total, up=up)
        return int(v)


class Node(KubeObject):
    kind = "Node"

    def __init__(self, name: str,
                 labels: Optional[Dict[str, str]] = None,
                 capacity: Resources = Resources(),
                 allocatable: Resources = Resources(),
                 taints: Sequence[Taint] = (),
                 provider_id: str = ""):
        self.metadata = ObjectMeta(name=name, labels=dict(labels or {}))
        self.capacity = capacity
        self.allocatable = allocatable
        self.taints = list(taints)
        self.provider_id = provider_id
        self.ready = False
        self.conditions: Dict[str, Condition] = {}


# ---------------------------------------------------------------------------
# Storage (PV / PVC / StorageClass) — the core scheduler's volume-topology
# inputs (core scheduling/volumetopology.go; exercised by the reference's
# storage E2E suite)
# ---------------------------------------------------------------------------

class StorageClass(KubeObject):
    kind = "StorageClass"

    def __init__(self, name: str,
                 provisioner: str = "ebs.csi.aws.com",
                 volume_binding_mode: str = "WaitForFirstConsumer",
                 allowed_topology_zones: Sequence[str] = ()):
        self.metadata = ObjectMeta(name=name)
        self.provisioner = provisioner
        self.volume_binding_mode = volume_binding_mode  # | Immediate
        #: allowedTopologies zone values ([] => any zone)
        self.allowed_topology_zones = list(allowed_topology_zones)


class PersistentVolume(KubeObject):
    kind = "PersistentVolume"

    def __init__(self, name: str, zone: str = "",
                 storage_class: str = "", capacity: str = "10Gi"):
        self.metadata = ObjectMeta(name=name)
        #: zonal EBS volumes carry a zone node-affinity; "" => zone-free
        self.zone = zone
        self.storage_class = storage_class
        self.capacity = capacity
        self.phase = "Available"   # | Bound


class PersistentVolumeClaim(KubeObject):
    kind = "PersistentVolumeClaim"

    def __init__(self, name: str, namespace: str = "default",
                 storage_class: str = "", volume_name: str = "",
                 requested: str = "10Gi"):
        self.metadata = ObjectMeta(name=name, namespace=namespace)
        self.storage_class = storage_class
        self.volume_name = volume_name  # bound PV ("" => unbound)
        self.requested = requested

    @property
    def bound(self) -> bool:
        return bool(self.volume_name)


# ---------------------------------------------------------------------------
# EC2NodeClass (infra template)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SelectorTerm:
    """Subnet/SG/AMI selector term: tags and/or id/name match
    (ec2nodeclass.go:141,156,174)."""
    tags: Tuple[Tuple[str, str], ...] = ()
    id: str = ""
    name: str = ""
    alias: str = ""   # AMI only: e.g. "al2023@latest" (ec2nodeclass.go:494-548)
    owner: str = ""

    @staticmethod
    def of(tags: Optional[Mapping[str, str]] = None, **kw) -> "SelectorTerm":
        return SelectorTerm(tags=tuple(sorted((tags or {}).items())), **kw)


@dataclass
class MetadataOptions:
    http_endpoint: str = "enabled"
    http_protocol_ipv6: str = "disabled"
    http_put_response_hop_limit: int = 1
    http_tokens: str = "required"  # IMDSv2


@dataclass
class BlockDeviceMapping:
    device_name: str = "/dev/xvda"
    volume_size: str = "20Gi"
    volume_type: str = "gp3"
    iops: Optional[int] = None
    throughput: Optional[int] = None
    encrypted: bool = True
    delete_on_termination: bool = True
    root_volume: bool = False


@dataclass
class KubeletConfiguration:
    """kubelet config subset (ec2nodeclass.go:212)."""
    max_pods: Optional[int] = None
    pods_per_core: Optional[int] = None
    kube_reserved: Dict[str, str] = field(default_factory=dict)
    system_reserved: Dict[str, str] = field(default_factory=dict)
    eviction_hard: Dict[str, str] = field(default_factory=dict)
    eviction_soft: Dict[str, str] = field(default_factory=dict)
    #: signal -> grace period; kubelet requires one per eviction_soft signal
    eviction_soft_grace_period: Dict[str, str] = field(default_factory=dict)
    cluster_dns: List[str] = field(default_factory=list)
    image_gc_high_threshold_percent: Optional[int] = None
    image_gc_low_threshold_percent: Optional[int] = None
    cpu_cfs_quota: Optional[bool] = None


class EC2NodeClass(KubeObject):
    kind = "EC2NodeClass"

    def __init__(self, name: str,
                 ami_selector_terms: Sequence[SelectorTerm] = (SelectorTerm(alias="al2023@latest"),),
                 subnet_selector_terms: Sequence[SelectorTerm] = (
                     SelectorTerm((("karpenter.sh/discovery", "*"),)),),
                 security_group_selector_terms: Sequence[SelectorTerm] = (
                     SelectorTerm((("karpenter.sh/discovery", "*"),)),),
                 role: str = "KarpenterNodeRole",
                 instance_profile: str = "",
                 user_data: str = "",
                 tags: Optional[Dict[str, str]] = None,
                 block_device_mappings: Sequence[BlockDeviceMapping] = (),
                 instance_store_policy: str = "",   # "" | "RAID0"
                 metadata_options: Optional[MetadataOptions] = None,
                 kubelet: Optional[KubeletConfiguration] = None,
                 detailed_monitoring: bool = False,
                 associate_public_ip: Optional[bool] = None):
        self.metadata = ObjectMeta(name=name)
        self.ami_selector_terms = list(ami_selector_terms)
        self.subnet_selector_terms = list(subnet_selector_terms)
        self.security_group_selector_terms = list(security_group_selector_terms)
        self.role = role
        self.instance_profile = instance_profile
        self.user_data = user_data
        self.tags = dict(tags or {})
        self.block_device_mappings = list(block_device_mappings)
        self.instance_store_policy = instance_store_policy
        self.metadata_options = metadata_options or MetadataOptions()
        self.kubelet = kubelet or KubeletConfiguration()
        self.detailed_monitoring = detailed_monitoring
        self.associate_public_ip = associate_public_ip
        # status (nodeclass controller fills these; ec2nodeclass_status.go:22-70)
        self.status_subnets: List[Dict[str, str]] = []       # {id, zone, zoneID}
        self.status_security_groups: List[Dict[str, str]] = []
        self.status_amis: List[Dict[str, Any]] = []          # {id, name, requirements}
        self.status_instance_profile: str = ""
        self.conditions: Dict[str, Condition] = {}

    @property
    def ami_family(self) -> str:
        """Resolve the AMI family from alias terms (ec2nodeclass.go:494-548)."""
        for t in self.ami_selector_terms:
            if t.alias:
                return t.alias.split("@", 1)[0]
        return "custom"

    @property
    def ami_version(self) -> str:
        for t in self.ami_selector_terms:
            if t.alias and "@" in t.alias:
                return t.alias.split("@", 1)[1]
        return "latest"

    def set_condition(self, ctype: str, status: str, reason: str = "",
                      message: str = "", now: float = 0.0) -> None:
        self.conditions[ctype] = Condition(ctype, status, reason, message, now)

    def condition_is(self, ctype: str, status: str = "True") -> bool:
        c = self.conditions.get(ctype)
        return c is not None and c.status == status

    @property
    def ready(self) -> bool:
        return self.condition_is("Ready")

    def hash(self) -> str:
        """Static-field hash for drift detection (ec2nodeclass.go:446-460,
        hash version v4)."""
        return stable_hash({
            "role": self.role,
            "instanceProfile": self.instance_profile,
            "userData": self.user_data,
            "tags": self.tags,
            "blockDeviceMappings": [vars(b) for b in self.block_device_mappings],
            "instanceStorePolicy": self.instance_store_policy,
            "metadataOptions": vars(self.metadata_options),
            "detailedMonitoring": self.detailed_monitoring,
            "associatePublicIP": self.associate_public_ip,
        })


def stable_hash(obj: Any) -> str:
    """Deterministic structure hash (stands in for hashstructure v2 ZeroNil)."""
    def _canon(o: Any) -> Any:
        if isinstance(o, Mapping):
            return {str(k): _canon(v) for k, v in sorted(o.items()) if v not in (None, {}, [], "")}
        if isinstance(o, (list, tuple)):
            return [_canon(v) for v in o]
        return o
    blob = json.dumps(_canon(obj), sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]
