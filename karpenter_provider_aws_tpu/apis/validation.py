"""Admission validation: the CEL-rule analog of the reference's CRD schemas.

The reference's user API is guarded by OpenAPI + CEL rules embedded in the
CRDs (pkg/apis/crds/karpenter.sh_nodepools.yaml,
karpenter.k8s.aws_ec2nodeclasses.yaml, 1,656 yaml lines; enforced by the
kube-apiserver). This module enforces the same rules — with
reference-shaped messages — at the fake API server's create/update
boundary, so malformed objects are rejected exactly where a real cluster
would reject them.

Covered rules (file:line cites into the reference CRDs):
- NodePool template requirements: restricted label domains
  (karpenter.sh_nodepools.yaml:271-283), minValues bounds and
  values-count floor (:284-330), In needs values, Gt/Lt single
  non-negative integer (:325-328);
- NodePool template labels: restricted domains (:198-210);
- disruption budgets: schedule must be set with duration (:139-141);
- nodeClassRef: group/kind/name non-empty (:234-248), group/kind
  immutable on update (:254-258);
- EC2NodeClass selector terms: list non-empty, per-term "at least one
  of", id/alias mutual exclusivity, alias format + supported families,
  empty tag keys/values (karpenter.k8s.aws_ec2nodeclasses.yaml:94-136,
  :493-533);
- blockDeviceMappings: at most one rootVolume (:237);
- kubelet: eviction signal keys, kubeReserved/systemReserved keys,
  imageGC threshold ordering, evictionSoft <-> grace matching (:285-374);
- restricted tags (apis/v1/labels.go:74-77).
"""

from __future__ import annotations

import re
from typing import Optional

from . import labels as L
from .requirements import Requirements

MIN_VALUES_MIN, MIN_VALUES_MAX = 1, 50

_KUBERNETES_IO_ALLOWED = {
    "beta.kubernetes.io/instance-type",
    "failure-domain.beta.kubernetes.io/region",
    "beta.kubernetes.io/os", "beta.kubernetes.io/arch",
    "failure-domain.beta.kubernetes.io/zone",
    "topology.kubernetes.io/zone", "topology.kubernetes.io/region",
    "node.kubernetes.io/instance-type",
    "kubernetes.io/arch", "kubernetes.io/os",
    "node.kubernetes.io/windows-build",
}
_KARPENTER_SH_ALLOWED = {L.CAPACITY_TYPE, L.NODEPOOL}

_EVICTION_SIGNALS = {"memory.available", "nodefs.available",
                     "nodefs.inodesFree", "imagefs.available",
                     "imagefs.inodesFree", "pid.available"}
_RESERVED_KEYS = {"cpu", "memory", "ephemeral-storage", "pid"}

_AMI_FAMILIES = ("al2", "al2023", "bottlerocket", "windows2019",
                 "windows2022")
_ALIAS_RE = re.compile(r"^[a-z0-9]+@[A-Za-z0-9.v-]+$")
#: ^((100|[0-9]{1,2})%|[0-9]+)$ — karpenter.sh_nodepools.yaml:111
_BUDGET_NODES_RE = re.compile(r"^((100|[0-9]{1,2})%|[0-9]+)$")


class ValidationError(ValueError):
    """Admission rejection — message mirrors the CRD CEL message."""


def _domain(key: str) -> str:
    return key.split("/")[0] if "/" in key else ""


def _dom_is(dom: str, suffix: str) -> bool:
    """Dot-anchored domain match: `dom` IS `suffix` or a subdomain of it
    (plain endswith would let foonode.kubernetes.io impersonate
    node.kubernetes.io — labels.py is_restricted_tag anchors the same way)."""
    return dom == suffix or dom.endswith("." + suffix)


def _check_restricted_label(key: str,
                            allow_nodepool: bool = False) -> Optional[str]:
    """Returns the reference-shaped message, or None if allowed.

    `allow_nodepool`: the NodeClaim CRD allowlists karpenter.sh/nodepool
    in requirements (karpenter.sh_nodeclaims.yaml:133) — the controller
    stamps it — while NodePool templates restrict it (:278-279)."""
    dom = _domain(key)
    if key == L.NODEPOOL and not allow_nodepool:
        return 'label "karpenter.sh/nodepool" is restricted'
    if key == L.HOSTNAME:
        return 'label "kubernetes.io/hostname" is restricted'
    if _dom_is(dom, "kubernetes.io"):
        if key in _KUBERNETES_IO_ALLOWED \
                or _dom_is(dom, "node.kubernetes.io") \
                or _dom_is(dom, "node-restriction.kubernetes.io"):
            return None
        return 'label domain "kubernetes.io" is restricted'
    if _dom_is(dom, "k8s.io") and not _dom_is(dom, "kops.k8s.io"):
        return 'label domain "k8s.io" is restricted'
    if _dom_is(dom, "karpenter.sh") and key not in _KARPENTER_SH_ALLOWED:
        return 'label domain "karpenter.sh" is restricted'
    if _dom_is(dom, "karpenter.k8s.aws") \
            and key not in L.AWS_REQUIREMENT_LABELS:
        return 'label domain "karpenter.k8s.aws" is restricted'
    return None


def _validate_requirements(reqs: Requirements,
                           allow_nodepool: bool = False) -> None:
    for r in reqs:
        msg = _check_restricted_label(r.key, allow_nodepool)
        if msg is not None:
            raise ValidationError(msg)
        if r.min_values is not None:
            if not (MIN_VALUES_MIN <= r.min_values <= MIN_VALUES_MAX):
                raise ValidationError(
                    f"minValues must be in [{MIN_VALUES_MIN}, "
                    f"{MIN_VALUES_MAX}], got {r.min_values}")
            # the CEL floor rule applies to In requirements
            # (karpenter.sh_nodepools.yaml:327-328)
            if not r.complement and r.greater_than is None \
                    and r.less_than is None \
                    and len(r.values) < r.min_values:
                raise ValidationError(
                    "requirements with 'minValues' must have at least that "
                    "many values specified in the 'values' field")
        if (r.greater_than is not None and r.greater_than < 0) \
                or (r.less_than is not None and r.less_than < 0):
            raise ValidationError(
                "requirements operator 'Gt' or 'Lt' must have a single "
                "positive integer value")
        if not r.complement and not r.values \
                and r.greater_than is None and r.less_than is None \
                and not r.impossible:
            # a plain In with zero values could never be satisfied; the CRD
            # rejects it at admission (yaml:325-326). (DoesNotExist compiles
            # to complement with empty values — allowed.)
            raise ValidationError(
                "requirements with operator 'In' must have a value defined")


def validate_nodepool(np) -> None:
    t = np.template
    _validate_requirements(t.requirements)
    for key in t.labels:
        msg = _check_restricted_label(key)
        if msg is not None:
            raise ValidationError(msg)
    ref = t.node_class_ref
    if not ref.name:
        raise ValidationError("name may not be empty")
    if not ref.kind:
        raise ValidationError("kind may not be empty")
    if not ref.group:
        raise ValidationError("group may not be empty")
    for b in np.disruption.budgets:
        if (b.schedule is None) != (b.duration is None):
            raise ValidationError("'schedule' must be set with 'duration'")
        if not _BUDGET_NODES_RE.match(b.nodes.strip()):
            raise ValidationError(f"invalid budget nodes value {b.nodes!r}")
        if b.schedule is not None:
            from ..utils.cron import Cron, CronError, parse_duration
            try:
                Cron(b.schedule)
                parse_duration(b.duration)
            except CronError as e:
                raise ValidationError(f"invalid budget schedule: {e}")


def validate_nodeclaim(nc) -> None:
    _validate_requirements(nc.requirements, allow_nodepool=True)
    if not nc.node_class_ref.name:
        raise ValidationError("name may not be empty")


def _validate_terms(terms, what: str, allow_name: bool = True,
                    allow_alias: bool = False) -> None:
    if not terms:
        raise ValidationError(f"{what} cannot be empty")
    fields = ["tags", "id"] + (["name"] if allow_name else []) \
        + (["alias"] if allow_alias else [])
    n_alias = sum(1 for t in terms if getattr(t, "alias", ""))
    for t in terms:
        present = [f for f in fields if getattr(t, f, None)]
        if not present:
            raise ValidationError(
                f"expected at least one, got none, {fields!r}")
        if t.id and len(present) > 1:
            raise ValidationError(
                f"'id' is mutually exclusive, cannot be set with a "
                f"combination of other fields in {what}")
        alias = getattr(t, "alias", "")
        if alias:
            if len(present) > 1:
                raise ValidationError(
                    "'alias' is mutually exclusive, cannot be set with a "
                    f"combination of other fields in {what}")
            if n_alias and len(terms) > 1:
                raise ValidationError(
                    "'alias' is mutually exclusive, cannot be set with a "
                    f"combination of other {what}")
            if "@" not in alias or not _ALIAS_RE.match(alias):
                raise ValidationError(
                    "'alias' is improperly formatted, must match the "
                    "format 'family@version'")
            family, version = alias.split("@", 1)
            if family not in _AMI_FAMILIES:
                raise ValidationError(
                    "family is not supported, must be one of the following: "
                    "'al2', 'al2023', 'bottlerocket', 'windows2019', "
                    "'windows2022'")
            if family.startswith("windows") and version != "latest":
                raise ValidationError(
                    "windows families may only specify version 'latest'")
        for k, v in (dict(t.tags) if t.tags else {}).items():
            if not k or not v:
                raise ValidationError(
                    "empty tag keys or values aren't supported")


def validate_ec2nodeclass(nc) -> None:
    _validate_terms(nc.ami_selector_terms, "amiSelectorTerms",
                    allow_alias=True)
    _validate_terms(nc.subnet_selector_terms, "subnetSelectorTerms",
                    allow_name=False)
    _validate_terms(nc.security_group_selector_terms,
                    "securityGroupSelectorTerms")
    if not nc.role and not nc.instance_profile:
        raise ValidationError("role cannot be empty")
    if sum(1 for b in nc.block_device_mappings if b.root_volume) > 1:
        raise ValidationError(
            "must have only one blockDeviceMappings with rootVolume")
    for key in nc.tags:
        if L.is_restricted_tag(key):
            raise ValidationError(f"tag {key!r} is restricted")
    k = nc.kubelet
    for field_name, allowed in (("eviction_hard", _EVICTION_SIGNALS),
                                ("eviction_soft", _EVICTION_SIGNALS),
                                ("eviction_soft_grace_period",
                                 _EVICTION_SIGNALS),
                                ("kube_reserved", _RESERVED_KEYS),
                                ("system_reserved", _RESERVED_KEYS)):
        for key in getattr(k, field_name, None) or {}:
            if key not in allowed:
                raise ValidationError(
                    f"valid keys for {_camel(field_name)} are "
                    f"{sorted(allowed)}")
    soft = getattr(k, "eviction_soft", None) or {}
    grace = getattr(k, "eviction_soft_grace_period", None) or {}
    for key in soft:
        if key not in grace:
            raise ValidationError(
                "evictionSoft OwnerKey does not have a matching "
                "evictionSoftGracePeriod")
    for key in grace:
        if key not in soft:
            raise ValidationError(
                "evictionSoftGracePeriod OwnerKey does not have a matching "
                "evictionSoft")
    high = getattr(k, "image_gc_high_threshold_percent", None)
    low = getattr(k, "image_gc_low_threshold_percent", None)
    if high is not None and low is not None and high <= low:
        raise ValidationError(
            "imageGCHighThresholdPercent must be greater than "
            "imageGCLowThresholdPercent")


def _camel(snake: str) -> str:
    parts = snake.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


def validate(obj) -> None:
    kind = getattr(obj, "kind", "")
    if kind == "NodePool":
        validate_nodepool(obj)
    elif kind == "NodeClaim":
        validate_nodeclaim(obj)
    elif kind == "EC2NodeClass":
        validate_ec2nodeclass(obj)


def validate_update(old, new) -> None:
    validate(new)
    kind = getattr(new, "kind", "")
    if kind == "NodePool":
        if new.template.node_class_ref.group != \
                old.template.node_class_ref.group:
            raise ValidationError("nodeClassRef.group is immutable")
        if new.template.node_class_ref.kind != \
                old.template.node_class_ref.kind:
            raise ValidationError("nodeClassRef.kind is immutable")
    elif kind == "EC2NodeClass":
        if old.role and new.role != old.role:
            raise ValidationError("immutable field changed")
