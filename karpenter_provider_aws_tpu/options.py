"""The flag/option system (pkg/operator/options/options.go:36-85).

The reference's 8 AWS flags with the same precedence chain — command-line
flag > environment variable > default (options.go:47-56) — plus validation
and context injection: options are registered as an *injectable* and carried
on a context object rather than as globals (coreoptions.Injectables,
options.go:30-32; FromContext/ToContext options.go:79-85).
"""

from __future__ import annotations

import argparse
import os
import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence


class OptionsError(ValueError):
    pass


class _Parser(argparse.ArgumentParser):
    """argparse converts type= exceptions into error() -> sys.exit(2);
    keep the parse() error contract uniform (OptionsError for both the
    flag and the env path) by raising instead of exiting."""

    def error(self, message: str):
        raise OptionsError(message)


#: (flag, env var, type, help) — options.go:36-45. Defaults live on the
#: Options dataclass (the single source of truth; parse() falls back to it).
_FLAGS = (
    ("cluster-name", "CLUSTER_NAME", str,
     "[REQUIRED] The kubernetes cluster name for resource discovery."),
    ("cluster-endpoint", "CLUSTER_ENDPOINT", str,
     "The external kubernetes cluster endpoint for new nodes to connect to. "
     "If not specified, will be discovered."),
    ("cluster-ca-bundle", "CLUSTER_CA_BUNDLE", str,
     "Cluster CA bundle for nodes to use for TLS connections with the API "
     "server. If not set, this is taken from the controller's TLS config."),
    ("isolated-vpc", "ISOLATED_VPC", bool,
     "If true, assume we can't reach AWS services which don't have a VPC "
     "endpoint. This also disables pricing lookups."),
    ("eks-control-plane", "EKS_CONTROL_PLANE", bool,
     "Marking this true means the cluster has an EKS control plane."),
    ("vm-memory-overhead-percent", "VM_MEMORY_OVERHEAD_PERCENT", float,
     "The VM memory overhead as a percent that will be subtracted from the "
     "instance type's memory."),
    ("interruption-queue", "INTERRUPTION_QUEUE", str,
     "Interruption queue is the name of the SQS queue used for processing "
     "interruption events from EC2. Interruption handling is disabled if "
     "not specified."),
    ("reserved-enis", "RESERVED_ENIS", int,
     "The number of ENIs reserved for system components (subtracted from "
     "the ENI-based max-pods calculation)."),
)


def _flag_attr(flag: str) -> str:
    return flag.replace("-", "_")


@dataclass
class Options:
    """The 8 AWS flags (options.go:36-85). Defaults match the reference:
    cluster-name is required (validate() rejects empty), interruption
    handling is off unless a queue is named."""
    cluster_name: str = ""
    cluster_endpoint: str = ""
    cluster_ca_bundle: str = ""
    isolated_vpc: bool = False
    eks_control_plane: bool = False
    vm_memory_overhead_percent: float = 0.075
    interruption_queue: str = ""
    reserved_enis: int = 0

    # -- flag binding (AddFlags + Parse, options.go:47-66) --------------
    @classmethod
    def add_flags(cls, parser: argparse.ArgumentParser) -> None:
        for flag, env, typ, help_ in _FLAGS:
            kwargs: Dict[str, Any] = {"help": f"{help_} (env {env})"}
            if typ is bool:
                kwargs["type"] = _parse_bool
                kwargs["nargs"] = "?"
                kwargs["const"] = True
            else:
                kwargs["type"] = typ
            parser.add_argument(f"--{flag}", dest=_flag_attr(flag),
                                default=None, **kwargs)

    @classmethod
    def parse(cls, argv: Sequence[str] = (),
              env: Optional[Dict[str, str]] = None) -> "Options":
        """flag > env var > default (options.go:47-56), then validate."""
        env = dict(os.environ if env is None else env)
        parser = _Parser(add_help=False)
        cls.add_flags(parser)
        ns, _ = parser.parse_known_args(list(argv))
        out = cls()
        for flag, env_key, typ, _ in _FLAGS:
            attr = _flag_attr(flag)
            val = getattr(ns, attr)
            if val is None and env_key in env:
                raw = env[env_key]
                if typ is bool:
                    val = _parse_bool(raw)
                else:
                    try:
                        val = typ(raw)
                    except ValueError:
                        raise OptionsError(
                            f"invalid value for {env_key}: {raw!r} "
                            f"(expected {typ.__name__})") from None
            if val is not None:
                setattr(out, attr, val)
        out.validate()
        return out

    # -- validation (options.go Validate) -------------------------------
    def validate(self) -> None:
        if not self.cluster_name:
            raise OptionsError("missing field, cluster-name")
        if self.cluster_endpoint and not re.match(
                r"^https?://", self.cluster_endpoint):
            raise OptionsError(
                f"not a valid clusterEndpoint URL: {self.cluster_endpoint!r}")
        if not (0.0 <= self.vm_memory_overhead_percent < 1.0):
            raise OptionsError(
                "vm-memory-overhead-percent cannot be negative or >= 1")
        if self.reserved_enis < 0:
            raise OptionsError("reserved-enis cannot be negative")


# ---------------------------------------------------------------------------
# context injection (coreoptions.Injectables / FromContext / ToContext)
# ---------------------------------------------------------------------------

class Context:
    """A context carrying injected values (the Go context.Context shape the
    reference threads options through; options.go:79-85)."""

    def __init__(self, parent: Optional["Context"] = None):
        self._values: Dict[type, Any] = dict(parent._values) if parent else {}

    def with_value(self, value: Any) -> "Context":
        child = Context(self)
        child._values[type(value)] = value
        return child

    def value(self, typ: type) -> Optional[Any]:
        return self._values.get(typ)


#: the injectables registry (options.go:30-32): everything injected into
#: the context at operator start
INJECTABLES: List[type] = [Options]


def to_context(ctx: Context, options: Options) -> Context:
    return ctx.with_value(options)


def from_context(ctx: Context) -> Options:
    opts = ctx.value(Options)
    if opts is None:
        raise OptionsError(
            "attempting to retrieve options from context, but options "
            "doesn't exist in context")
    return opts


def _parse_bool(s) -> bool:
    """strconv.ParseBool semantics: unrecognized values are errors, not
    False (a typo'd ISOLATED_VPC must not silently invert behavior)."""
    if isinstance(s, bool):
        return s
    v = str(s).strip().lower()
    if v in ("1", "t", "true", "yes", "on"):
        return True
    if v in ("0", "f", "false", "no", "off"):
        return False
    raise OptionsError(f"invalid boolean value {s!r}")
