"""The operator: constructs every provider/controller and wires the manager.

Mirrors cmd/controller/main.go:28-74 + pkg/operator/operator.go:76-205: the
operator builds clients (here: the fake cloud), discovers cluster facts,
constructs every provider singleton with its cache, then registers core +
provider controllers on one manager. ``step()`` runs one reconcile round of
every controller in dependency order; ``run_until_settled()`` drives the
loop to a fixed point (the envtest-style test harness).
"""

from __future__ import annotations

import threading
import time

from typing import Optional

from .cache.ttl import UnavailableOfferings
from .cloudprovider.provider import CloudProvider
from .controllers.disruption import DisruptionController
from .controllers.lifecycle import (NodeClaimLifecycle,
                                    NodeRepairController, Terminator)
from .controllers.provisioning import Provisioner
from .controllers.steady_state import (CatalogController,
                                       DiscoveredCapacityController,
                                       GarbageCollector,
                                       InterruptionController,
                                       StaticHashController,
                                       NodeClassStatusController,
                                       PricingController,
                                       SSMInvalidationController, Tagger,
                                       VersionController)
from .fake.catalog import catalog_by_name
from .fake.ec2 import FakeEC2
from .fake.iam import FakeIAM
from .fake.kube import FakeKube
from .fake.kubelet import FakeKubelet
from .options import Options
from .providers.amifamily import AMIProvider
from .providers.instance import InstanceProvider
from .providers.instancetype import InstanceTypeProvider
from .providers.launchtemplate import LaunchTemplateProvider
from .providers.network import SecurityGroupProvider, SubnetProvider
from .providers.instanceprofile import InstanceProfileProvider
from .providers.pricing import PricingProvider
from .providers.sqs import SQSProvider
from .providers.version import VersionProvider
from .providers.ssm import SSMProvider
from .solver.cpu import CPUSolver
from .solver.types import Solver
from .state.cluster import ClusterState
from .utils.events import Recorder
from .utils.metrics import Metrics


class PreflightError(RuntimeError):
    """Boot preflight failed: the cloud seam is dead or wedged. The
    daemon exits with this error instead of starting controllers that
    would silently spin against an unreachable cloud."""


def _with_deadline(fn, deadline_s: float, what: str):
    """Run ``fn`` with a hard wall-clock deadline. A wedged link BLOCKS
    rather than erroring (the same failure mode as the accelerator
    tunnel, solver/route.py), so an in-thread try/except cannot defend —
    the call runs in a worker thread and an overrun raises PreflightError
    while the daemon can still exit fast."""
    out: dict = {}

    def _run():
        try:
            out["v"] = fn()
        except Exception as e:  # re-raised typed below
            out["e"] = e

    t = threading.Thread(target=_run, daemon=True, name="preflight")
    t.start()
    t.join(deadline_s)
    if t.is_alive():
        raise PreflightError(
            f"{what} did not respond within {deadline_s:.0f}s "
            "(cloud link wedged?)")
    if "e" in out:
        raise PreflightError(f"{what} failed: {out['e']}")
    return out.get("v")


class Operator:
    def _check_ec2_connectivity(self) -> bool:
        """CheckEC2Connectivity (operator.go:218-227): issue the dry-run
        and require the DryRunOperation marker — any other outcome (a
        normal return, an auth error, a transport error) is a dead seam."""
        from .fake.ec2 import DryRunOperation
        try:
            self.ec2.dry_run_describe_instance_types()
        except DryRunOperation:
            return True
        raise ConnectionError(
            "dry-run DescribeInstanceTypes returned without the "
            "DryRunOperation marker")

    def __init__(self, options: Optional[Options] = None,
                 ec2: Optional[FakeEC2] = None,
                 solver: Optional[Solver] = None,
                 consolidation_evaluator=None,
                 clock=time.time,
                 preflight_deadline: float = 5.0):
        self.options = options or Options(
            cluster_name="cluster",
            cluster_endpoint="https://cluster.local",
            eks_control_plane=True,
            interruption_queue="karpenter-interruption")
        self.clock = clock
        # the fake cloud shares the operator clock so launch times and
        # controller grace windows (GC's 30s, interruption ages) cohere
        # under test clocks
        self.ec2 = ec2 or FakeEC2(now=clock)
        self.kube = FakeKube(now=clock)
        # boot preflight (operator.go:111-115,218-227): discover the
        # region from IMDS and prove the EC2 seam answers a dry-run —
        # fail fast (< preflight_deadline) on a dead or wedged cloud
        # link instead of starting controllers that would spin forever
        self.region = _with_deadline(
            self.ec2.imds_region, preflight_deadline,
            "IMDS region discovery")
        _with_deadline(
            self._check_ec2_connectivity, preflight_deadline,
            "EC2 connectivity preflight (dry-run DescribeInstanceTypes)")
        self.metrics = Metrics()
        self.recorder = Recorder(clock=clock)

        # cloud-API resilience seam (providers/awsretry.py): AWS-style
        # error classification + bounded full-jitter retries + adaptive
        # client-side rate limiting, wrapped around every EC2/SSM/EKS
        # call site below (aws-sdk-go-v2 standard+adaptive retryer
        # analog). The preflight above deliberately ran RAW: fail-fast
        # on a dead seam must not be retried into a slow boot.
        from .providers.awsretry import CloudRetryPolicy, ResilientCloud
        self.cloud_retry = CloudRetryPolicy(metrics=self.metrics)
        self.cloud = ResilientCloud(self.ec2, self.cloud_retry)
        self.cloud_retry.emit_state()

        # providers (operator.go:139-186)
        # the operator clock reaches the TTL layers too: a virtual-time
        # endurance run must age the ICE blacklist and catalog caches
        # on the same timeline as the GC/interruption grace windows
        self.unavailable_offerings = UnavailableOfferings(clock=clock)
        self.instance_types = InstanceTypeProvider(
            vm_memory_overhead_percent=self.options.vm_memory_overhead_percent,
            unavailable_offerings=self.unavailable_offerings,
            reserved_enis=self.options.reserved_enis, clock=clock)
        self.pricing = PricingProvider(self.cloud)
        self.subnets = SubnetProvider(self.cloud)
        self.security_groups = SecurityGroupProvider(self.cloud)
        self.ssm = SSMProvider(self.cloud)
        self.amis = AMIProvider(self.cloud, ssm=self.ssm)
        self.iam = FakeIAM()
        self.instance_profiles = InstanceProfileProvider(
            self.options.cluster_name, region=self.region, iam=self.iam)
        self.version = VersionProvider()
        self.sqs = SQSProvider(self.options.interruption_queue)
        # kube-dns discovery (operator.go:243-260,262-274): the reference
        # reads kube-system/kube-dns's ClusterIP; EKS assigns it the 10th
        # address of the service CIDR, so the fake derives it from the
        # cluster's (IPv6-preferred) service CIDR
        import ipaddress
        svc_cidr = (getattr(self.ec2, "eks_service_ipv6_cidr", None)
                    or getattr(self.ec2, "eks_cluster_cidr", None))
        self.kube_dns_ip = (
            str(ipaddress.ip_network(svc_cidr)[10]) if svc_cidr else "")
        self.launch_templates = LaunchTemplateProvider(
            self.cloud, self.amis, self.security_groups,
            cluster_name=self.options.cluster_name,
            cluster_endpoint=self.options.cluster_endpoint,
            ca_bundle=self.options.cluster_ca_bundle,
            kube_dns_ip=self.kube_dns_ip)
        self.instances = InstanceProvider(
            self.cloud, self.subnets, self.launch_templates,
            self.unavailable_offerings,
            cluster_name=self.options.cluster_name, metrics=self.metrics)

        # the plugin boundary + core state (main.go:31-40); the metrics
        # decorator wraps it before any controller sees it (main.go:39)
        from .cloudprovider.decorator import MetricsDecorator
        self.cloudprovider = MetricsDecorator(
            CloudProvider(
                self.kube, self.instance_types, self.instances,
                cluster_name=self.options.cluster_name, clock=clock,
                recorder=self.recorder),
            self.metrics, clock=clock)
        self.state = ClusterState(self.kube, clock=clock)


        # controllers (controllers.go:63-101 + core)
        self.solver = solver or CPUSolver()
        if hasattr(self.solver, "metrics"):
            self.solver.metrics = self.metrics
        if consolidation_evaluator is not None \
                and hasattr(consolidation_evaluator, "metrics"):
            consolidation_evaluator.metrics = self.metrics
        # preemption search rides the SAME solver instance: a TPU-backed
        # operator evaluates victim sets on the device, a CPU one on the
        # planner's bit-identical numpy twin
        from .scheduling import PreemptionPlanner
        self.preempt_planner = PreemptionPlanner(solver=self.solver,
                                                 metrics=self.metrics)
        self.provisioner = Provisioner(self.kube, self.state,
                                       self.cloudprovider, self.solver,
                                       metrics=self.metrics, clock=clock,
                                       preempt_planner=self.preempt_planner)
        self.lifecycle = NodeClaimLifecycle(self.kube, self.cloudprovider,
                                            self.instance_types, clock=clock,
                                            recorder=self.recorder,
                                            metrics=self.metrics,
                                            state=self.state)
        self.terminator = Terminator(self.kube, self.cloudprovider,
                                     clock=clock, metrics=self.metrics)
        self.node_repair = NodeRepairController(
            self.kube, self.cloudprovider, clock=clock,
            metrics=self.metrics, recorder=self.recorder)
        self.nodeclass_status = NodeClassStatusController(
            self.kube, self.subnets, self.security_groups, self.amis,
            self.instance_profiles, clock=clock, metrics=self.metrics,
            recorder=self.recorder)
        self.gc = GarbageCollector(self.kube, self.cloudprovider, clock=clock,
                                   metrics=self.metrics)
        self.tagger = Tagger(self.kube, self.instances,
                             cluster_name=self.options.cluster_name)
        self.interruption = InterruptionController(
            self.kube, self.sqs, self.unavailable_offerings,
            metrics=self.metrics, clock=clock, recorder=self.recorder,
            ec2=self.cloud)
        self.catalog_controller = CatalogController(
            self.cloud, self.instance_types, metrics=self.metrics,
            unavailable_offerings=self.unavailable_offerings,
            pricing=self.pricing)
        self.pricing_controller = PricingController(self.pricing)
        self.nodeclass_hash = StaticHashController(self.kube)
        self.discovered_capacity = DiscoveredCapacityController(
            self.kube, self.instance_types)
        self.ssm_invalidation = SSMInvalidationController(
            self.cloud, self.amis, ssm=self.ssm, clock=clock)
        self.version_controller = VersionController(
            self.version, source=self.cloud.eks_describe_cluster_version,
            clock=clock)
        self.disruption = DisruptionController(
            self.kube, self.state, self.cloudprovider, self.solver,
            self.provisioner, evaluator=consolidation_evaluator,
            metrics=self.metrics, clock=clock)

        # node-join simulation (the E2E "real cluster" analog)
        self.kubelet = FakeKubelet(self.kube, self.ec2,
                                   catalog_by_name(self.ec2.catalog),
                                   self.state, clock=clock,
                                   vm_overhead_percent=self.options.vm_memory_overhead_percent,
                                   reserved_enis=self.options.reserved_enis,
                                   metrics=self.metrics)

        # fleet-ops telemetry: walk-the-world gauge families + the
        # client-go / aws-sdk boundary series (controllers/telemetry.py)
        from .controllers.telemetry import (TelemetryEmitter,
                                            instrument_ec2, instrument_kube)
        self.telemetry = TelemetryEmitter(self.kube, self.state,
                                          self.metrics, clock=clock)
        instrument_kube(self.kube, self.metrics)
        instrument_ec2(self.ec2, self.metrics)
        from . import __version__ as _version
        self.metrics.set_gauge(
            "karpenter_build_info", 1.0,
            labels={"version": _version, "solver": self.solver.name})

        # boot-blocking hydration (operator.go:152-155): catalog + pricing
        t_boot = time.perf_counter()
        # pricing BEFORE catalog: the catalog prices offerings through
        # the pricing provider, and until the first live spot refresh
        # the provider serves the zone-agnostic static default — which
        # must not mint spot offerings in zones with no spot market
        # (local zones). Same settling order as the reference's boot
        # (version/pricing hydrate synchronously, operator.go:152-155).
        self.pricing_controller.reconcile()
        self.catalog_controller.reconcile()
        self.metrics.set_gauge("karpenter_cluster_state_unsynced_time_seconds",
                               time.perf_counter() - t_boot)
        self.metrics.set_gauge("karpenter_cluster_state_synced", 1.0)

    # ------------------------------------------------------------------
    def step(self, disrupt: bool = True) -> dict:
        """One reconcile round of every controller, dependency order."""
        out = {}
        out["nodeclass"] = self.nodeclass_status.reconcile()
        out["interruption"] = self.interruption.reconcile()
        out["disrupted"] = (self.disruption.reconcile() is not None) \
            if disrupt else False
        out["repaired"] = self.node_repair.reconcile()
        out["terminated"] = self.terminator.reconcile()
        prov = self.provisioner.reconcile()
        out["provisioned"] = len(prov.created_claims)
        out["unschedulable"] = len(prov.unschedulable)
        out["lifecycle"] = self.lifecycle.reconcile()
        out["joined"] = self.kubelet.tick()
        out["lifecycle2"] = self.lifecycle.reconcile()
        out["tagged"] = self.tagger.reconcile()
        out["gc"] = self.gc.reconcile()
        out["hash_restamped"] = self.nodeclass_hash.reconcile()
        out["capacity_discovered"] = self.discovered_capacity.reconcile()
        out["ssm_evicted"] = self.ssm_invalidation.reconcile()
        out["version_changed"] = self.version_controller.reconcile()
        self._emit_state_gauges()
        self.telemetry.reconcile()
        return out

    def _emit_state_gauges(self) -> None:
        """Cluster-state gauges (metrics.md cluster_state/nodepools
        groups): node count, per-nodepool usage."""
        nodes = self.kube.list("Node")
        self.metrics.set_gauge("karpenter_cluster_state_node_count",
                               len(nodes))
        # full re-emit: drop series for pools that vanished so the gauge
        # never shows phantom usage (the steady_state.py ghost pattern)
        self.metrics.clear_series("karpenter_nodepools_usage")
        for np_name, used in self.state.nodepool_usage().items():
            for dim in ("cpu", "memory"):
                self.metrics.set_gauge(
                    "karpenter_nodepools_usage",
                    used[dim],
                    labels={"nodepool": np_name, "resource_type": dim})

    def run_until_settled(self, max_steps: int = 20,
                          disrupt: bool = True) -> int:
        """Step until a fixed point: no pending pods, no mid-lifecycle
        claims, nothing terminated/GC'd/disrupted this round."""
        for i in range(max_steps):
            out = self.step(disrupt=disrupt)
            quiet = (not self.state.pending_pods()
                     and out["provisioned"] == 0
                     and out["terminated"] == 0
                     and out["joined"] == 0
                     and out["gc"] == 0
                     and not out["disrupted"]
                     and not (disrupt and self.disruption._in_flight)
                     and all(v == 0 for v in out["lifecycle"].values())
                     and all(v == 0 for v in out["lifecycle2"].values()))
            if quiet:
                return i + 1
        return max_steps
