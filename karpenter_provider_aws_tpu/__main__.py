"""``python -m karpenter_provider_aws_tpu`` — the controller process
(cmd/controller/main.go:28-74)."""

import sys

from .daemon import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
