"""Steady-state controllers: NodeClass status, GC, tagging, interruption,
catalog/pricing refresh (SURVEY §2.5).

- NodeClassStatus: sequential sub-reconcilers ami -> subnet -> securitygroup
  -> instanceprofile -> validation -> readiness (nodeclass/controller.go:91-140).
- GarbageCollector: CloudProvider.List vs cluster NodeClaims; terminate
  instances with no NodeClaim after a 30s grace (garbagecollection/
  controller.go:55-90).
- Tagger: stamp Name/cluster/nodeclaim tags post-registration
  (tagging/controller.go:61-89).
- InterruptionController: SQS long-poll; spot interruption / rebalance /
  scheduled change / state change -> CordonAndDrain (delete NodeClaim) and
  blacklist the spot offering (interruption/controller.go:94-134,299+).
- CatalogController / PricingController: the 12h refresh loops
  (providers/instancetype/controller.go:43-60, providers/pricing/controller.go:43-60).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Set

from ..apis import labels as L
from ..apis.objects import EC2NodeClass
from ..cloudprovider.provider import CloudProvider, parse_instance_id
from ..cloudprovider.types import NodeClaimNotFoundError
from ..fake.kube import FakeKube, NotFound
from ..providers.amifamily import AMIProvider
from ..providers.instance import InstanceProvider
from ..providers.instancetype import InstanceTypeProvider, OfferingsSnapshot
from ..providers.network import SecurityGroupProvider, SubnetProvider
from ..providers.instanceprofile import InstanceProfileProvider
from ..providers.pricing import PricingProvider
from ..providers.sqs import InterruptionMessage, SQSProvider

log = logging.getLogger(__name__)

GC_GRACE_SECONDS = 30.0


class NodeClassStatusController:
    def __init__(self, kube: FakeKube, subnet: SubnetProvider,
                 sg: SecurityGroupProvider, ami: AMIProvider,
                 profiles: InstanceProfileProvider, clock=time.time,
                 metrics=None, recorder=None):
        self.kube = kube
        self.subnet = subnet
        self.sg = sg
        self.ami = ami
        self.profiles = profiles
        self.clock = clock
        self.metrics = metrics
        self.recorder = recorder
        #: last observed Ready status per nodeclass (transition events)
        self._ready_seen: Dict[str, str] = {}

    def _emit_conditions(self, nc: EC2NodeClass) -> None:
        """The status-controller decorations (controllers.go:91,
        operatorpkg status): one gauge per condition and an event on
        Ready transitions."""
        if self.metrics is not None:
            for cond in nc.conditions.values():
                self.metrics.set_gauge(
                    "operator_status_condition_current_status",
                    1.0 if cond.status == "True" else 0.0,
                    labels={"kind": "EC2NodeClass",
                            "name": nc.metadata.name,
                            "type": cond.type})
        ready = nc.conditions.get("Ready")
        if ready is None:
            return
        prev = self._ready_seen.get(nc.metadata.name)
        if prev != ready.status:
            self._ready_seen[nc.metadata.name] = ready.status
            if self.recorder is not None and prev is not None:
                self.recorder.publish(
                    "EC2NodeClass", nc.metadata.name,
                    "Ready" if ready.status == "True" else "NotReady",
                    f"EC2NodeClass {nc.metadata.name} became "
                    f"{'ready' if ready.status == 'True' else 'not ready'}",
                    "Normal" if ready.status == "True" else "Warning")

    def reconcile(self) -> int:
        n = 0
        live = {nc.metadata.name for nc in self.kube.list("EC2NodeClass")
                if nc.metadata.deletion_timestamp is None}
        # deleted/deleting nodeclasses: drop their condition series and
        # transition state, so dashboards never see a healthy ghost and a
        # recreated same-name class gets a fresh first Ready event
        for gone in [name for name in self._ready_seen if name not in live]:
            del self._ready_seen[gone]
            if self.metrics is not None:
                self.metrics.clear_series(
                    "operator_status_condition_current_status",
                    match={"kind": "EC2NodeClass", "name": gone})
        for nc in self.kube.list("EC2NodeClass"):
            if nc.metadata.deletion_timestamp is not None:
                # termination path: hold the finalizer while NodeClaims
                # still reference this class (running capacity keeps its
                # IAM binding; the reference termination controller
                # requeues the same way), then reap the instance profile
                # this class created (instanceprofile.go Delete — a spec-
                # pinned profile is user-managed and never touched) and
                # release the finalizer so deletion completes
                if "karpenter.k8s.aws/termination" in nc.metadata.finalizers:
                    held = any(
                        c.node_class_ref.name == nc.metadata.name
                        for c in self.kube.list("NodeClaim"))
                    if not held:
                        self.profiles.delete(nc)
                        self.kube.remove_finalizer(
                            nc, "karpenter.k8s.aws/termination")
                        n += 1
                continue
            if "karpenter.k8s.aws/termination" not in nc.metadata.finalizers:
                nc.metadata.finalizers.append("karpenter.k8s.aws/termination")
            now = self.clock()
            ok = True
            # ami -> subnet -> securitygroup -> instanceprofile -> validation
            amis = self.ami.list(nc)
            nc.status_amis = [{"id": a.id, "name": a.name, "arch": a.arch}
                              for a in amis]
            nc.set_condition("AMIsReady", "True" if amis else "False",
                             "" if amis else "NoAMIs", now=now)
            ok &= bool(amis)
            subnets = self.subnet.list(nc)
            nc.status_subnets = [{"id": s.id, "zone": s.zone,
                                  "zoneID": s.zone_id} for s in subnets]
            nc.set_condition("SubnetsReady", "True" if subnets else "False",
                             "" if subnets else "NoSubnets", now=now)
            ok &= bool(subnets)
            sgs = self.sg.list(nc)
            nc.status_security_groups = [{"id": g} for g in sgs]
            nc.set_condition("SecurityGroupsReady",
                             "True" if sgs else "False",
                             "" if sgs else "NoSecurityGroups", now=now)
            ok &= bool(sgs)
            nc.status_instance_profile = self.profiles.create(nc)
            nc.set_condition("InstanceProfileReady", "True", now=now)
            nc.set_condition("ValidationSucceeded", "True", now=now)
            nc.set_condition("Ready", "True" if ok else "False", now=now)
            self.kube.update(nc)
            self._emit_conditions(nc)
            n += 1
        return n


class GarbageCollector:
    def __init__(self, kube: FakeKube, cloudprovider: CloudProvider,
                 clock=time.time, metrics=None):
        self.kube = kube
        self.cloudprovider = cloudprovider
        self.clock = clock
        self.metrics = metrics

    #: termination fan-out width (garbagecollection/controller.go:80:
    #: workqueue.ParallelizeUntil(ctx, 100, ...)); parallel callers feed
    #: the TerminateInstances micro-batcher, which coalesces them into
    #: few API calls
    WORKERS = 100

    def reconcile(self) -> int:
        """Terminate cloud instances with no NodeClaim (>30s old)."""
        claimed = {c.provider_id for c in self.kube.list("NodeClaim")
                   if c.provider_id}
        now = self.clock()
        doomed = []
        for claim in self.cloudprovider.list():
            pid = claim.provider_id
            if pid in claimed:
                continue
            instance = self.cloudprovider.instances.get(parse_instance_id(pid))
            if now - instance.launch_time < GC_GRACE_SECONDS:
                continue
            doomed.append(instance.id)
        reaped = 0
        if doomed:
            from concurrent.futures import ThreadPoolExecutor

            def reap(iid):
                try:
                    self.cloudprovider.instances.delete(iid)
                    return 1
                except NodeClaimNotFoundError:
                    return 0

            with ThreadPoolExecutor(
                    max_workers=min(self.WORKERS, len(doomed))) as pool:
                reaped = sum(pool.map(reap, doomed))
        # also reap Node objects whose instance is gone
        live = {i.provider_id for i in self.cloudprovider.instances.list()}
        # raw visibility across ALL states (the default describe filter
        # hides "terminated"): a VISIBLY terminated instance is dead and
        # reaped immediately, but one the API has never heard of may not
        # have converged into DescribeInstances yet — young objects in
        # that state get the eventual-consistency grace instead of a reap
        # (chaos must never GC a node that is still materializing)
        from .lifecycle import (ALL_INSTANCE_STATES, CREATION_GRACE_SECONDS,
                                creation_age, drain_node_pods)
        known = {i.provider_id for i in
                 self.cloudprovider.instances.ec2.describe_instances(
                     states=ALL_INSTANCE_STATES)}

        def _grace(controller: str) -> None:
            if self.metrics is not None:
                self.metrics.inc(
                    "karpenter_cloud_eventual_consistency_grace_total",
                    labels={"controller": controller})

        for node in self.kube.list("Node"):
            if node.provider_id and node.provider_id not in live \
                    and not node.ready:
                if node.provider_id not in known \
                        and now - node.metadata.creation_timestamp \
                        < CREATION_GRACE_SECONDS:
                    _grace("gc-node")
                    continue
                self.kube.delete("Node", node.metadata.name)
        # ...and NodeClaims whose launched instance vanished behind the
        # cluster's back (the core nodeclaim GC direction: instance
        # terminated externally -> claim+node deleted, pods reschedule).
        # Pods are drained by name regardless of whether the Node object
        # still exists — the node-reap loop above may have deleted it in
        # this same pass, and bound pods must never outlive their node.
        for claim in self.kube.list("NodeClaim"):
            if claim.metadata.deletion_timestamp is not None:
                # already terminating: the Terminator owns its drain,
                # metrics, and cleanup (it handles instance-gone itself)
                continue
            if claim.launched and claim.provider_id \
                    and claim.provider_id not in live:
                if claim.provider_id not in known \
                        and creation_age(claim, now) < CREATION_GRACE_SECONDS:
                    # invisible (not terminated) + young: DescribeInstances
                    # has not converged on this launch yet
                    _grace("gc-nodeclaim")
                    continue
                if claim.node_name:
                    drain_node_pods(self.kube, claim.node_name,
                                    metrics=self.metrics)
                    if self.kube.try_get("Node", claim.node_name):
                        self.kube.delete("Node", claim.node_name)
                self.kube.remove_finalizer(claim, "karpenter.sh/termination")
                if self.kube.try_get("NodeClaim", claim.name):
                    self.kube.delete("NodeClaim", claim.name)
                reaped += 1
        return reaped


class Tagger:
    def __init__(self, kube: FakeKube, instances: InstanceProvider,
                 cluster_name: str = "cluster"):
        self.kube = kube
        self.instances = instances
        self.cluster_name = cluster_name
        self._done: Set[str] = set()

    def reconcile(self) -> int:
        n = 0
        for claim in self.kube.list("NodeClaim"):
            if not claim.registered or claim.uid in self._done \
                    or not claim.provider_id:
                continue
            instance_id = parse_instance_id(claim.provider_id)
            try:
                self.instances.create_tags(instance_id, {
                    "Name": f"{claim.nodepool}/{claim.name}",
                    "karpenter.sh/nodeclaim": claim.name,
                    "eks:eks-cluster-name": self.cluster_name,
                })
                self._done.add(claim.uid)
                n += 1
            except NodeClaimNotFoundError:
                pass
        return n


ACTIONABLE_KINDS = {"spot_interruption", "rebalance_recommendation",
                    "scheduled_change", "state_change"}


class InterruptionController:
    def __init__(self, kube: FakeKube, sqs: SQSProvider,
                 unavailable_offerings, metrics=None, clock=time.time,
                 recorder=None, ec2=None):
        self.kube = kube
        self.sqs = sqs
        self.unavailable = unavailable_offerings
        self.metrics = metrics
        self.clock = clock
        self.recorder = recorder
        #: the fake cloud, for compressing AWS's spot reclaim into the
        #: handling instant (see _handle) — None in unit tests that only
        #: exercise message parsing
        self.ec2 = ec2
        # at-least-once delivery state: SQS may deliver a message twice,
        # out of order, or redeliver after a crash mid-handle. Actionable
        # messages are keyed by (kind, instance_id); a key already handled
        # (within DEDUPE_TTL) or currently in flight on another worker is
        # acknowledged without re-handling, so a spot reclaim processed
        # twice never double-terminates or double-counts a cordon.
        import threading
        self._dedupe_mu = threading.Lock()
        self._handled_keys: Dict[tuple, float] = {}
        self._inflight_keys: Set[tuple] = set()

    #: message-handling fan-out width (interruption/controller.go:116:
    #: workqueue.ParallelizeUntil(ctx, 10, ...))
    WORKERS = 10

    #: how long a handled (kind, instance) key suppresses redeliveries —
    #: comfortably past SQS's redrive horizon for the fake's timescales
    DEDUPE_TTL = 600.0

    def _dedupe_check(self, msg: InterruptionMessage) -> bool:
        """True when this message is a duplicate to acknowledge-and-drop."""
        if msg.kind not in ACTIONABLE_KINDS:
            return False
        key = (msg.kind, msg.instance_id)
        now = self.clock()
        with self._dedupe_mu:
            done = self._handled_keys.get(key)
            if done is not None and now - done < self.DEDUPE_TTL:
                return True
            if key in self._inflight_keys:
                return True  # a concurrent worker owns this key's handling
            self._inflight_keys.add(key)
            return False

    def _dedupe_commit(self, msg: InterruptionMessage, ok: bool) -> None:
        """Mark the key handled only AFTER a successful handle — a crash
        mid-handle leaves the message undeleted and the key unclaimed, so
        the redelivery is processed (at-least-once, never at-most-once)."""
        if msg.kind not in ACTIONABLE_KINDS:
            return
        key = (msg.kind, msg.instance_id)
        with self._dedupe_mu:
            self._inflight_keys.discard(key)
            if ok:
                self._handled_keys[key] = self.clock()
                if len(self._handled_keys) > 4096:
                    cutoff = self.clock() - self.DEDUPE_TTL
                    self._handled_keys = {
                        k: t for k, t in self._handled_keys.items()
                        if t >= cutoff}

    def reconcile(self) -> Dict[str, int]:
        stats = {"handled": 0, "cordoned": 0, "noop": 0, "deduped": 0}
        claims_by_instance = {}
        for c in self.kube.list("NodeClaim"):
            if c.provider_id:
                claims_by_instance[parse_instance_id(c.provider_id)] = c
        from concurrent.futures import ThreadPoolExecutor

        def work(msg):
            local = {"handled": 0, "cordoned": 0, "noop": 0, "deduped": 0}
            t_recv = self.clock()
            if self._dedupe_check(msg):
                self.sqs.delete(msg)
                local["deduped"] += 1
                if self.metrics is not None:
                    self.metrics.inc(
                        "karpenter_interruption_deduped_messages_total",
                        labels={"message_type": msg.kind})
                    self.metrics.inc(
                        "karpenter_interruption_deleted_messages_total",
                        labels={"message_type": msg.kind})
                return local
            try:
                self._handle(msg, claims_by_instance, local)
            except BaseException:
                self._dedupe_commit(msg, ok=False)
                raise
            self._dedupe_commit(msg, ok=True)
            self.sqs.delete(msg)
            local["handled"] += 1
            if self.metrics is not None:
                self.metrics.inc(
                    "karpenter_interruption_received_messages_total",
                    labels={"message_type": msg.kind})
                self.metrics.inc(
                    "karpenter_interruption_deleted_messages_total",
                    labels={"message_type": msg.kind})
                # receive -> delete residency (the reference measures SQS
                # SentTimestamp -> delete; the fake has no transport delay
                # so handling time is the whole queue residency)
                self.metrics.observe(
                    "karpenter_interruption_message_queue"
                    "_duration_seconds", max(0.0, self.clock() - t_recv))
            return local

        with ThreadPoolExecutor(max_workers=self.WORKERS) as pool:
            while True:
                # drain in waves: receive() is non-destructive until
                # delete, so take one deep batch per wave and fan it out
                # 10-wide (the reference long-polls batches and hands them
                # to ParallelizeUntil)
                wave = self.sqs.receive(max_messages=10 * self.WORKERS)
                if not wave:
                    break
                for local in pool.map(work, wave):
                    for k, v in local.items():
                        stats[k] += v
        return stats

    def _handle(self, msg: InterruptionMessage, claims, stats) -> None:
        if msg.kind not in ACTIONABLE_KINDS:
            stats["noop"] += 1
            return
        claim = claims.get(msg.instance_id)
        if claim is None:
            stats["noop"] += 1
            return
        if msg.kind == "spot_interruption":
            # blacklist the offering so the replacement avoids the pool
            itype = claim.metadata.labels.get(L.INSTANCE_TYPE, "")
            zone = claim.metadata.labels.get(L.ZONE, "")
            if itype and zone:
                self.unavailable.mark_unavailable(
                    L.CAPACITY_TYPE_SPOT, itype, zone, reason="SpotInterruption")
            # EC2 reclaims a spot instance ~2 minutes after the warning
            # regardless of drain progress; the fake environment has no
            # independent AWS actor, so the reclaim is compressed into
            # the handling instant. The terminator sees the instance
            # gone and skips the (moot) ordered drain — upstream's
            # instance-not-found cleanup path.
            if self.ec2 is not None:
                self.ec2.terminate_instances([msg.instance_id])
        self._publish_events(msg, claim)
        if msg.kind in ACTIONABLE_KINDS:
            # CordonAndDrain: delete the claim; termination drains + replaces
            try:
                self.kube.delete("NodeClaim", claim.metadata.name)
            except NotFound:
                pass  # a concurrent message already cordoned this claim
            else:
                stats["cordoned"] += 1

    def _publish_events(self, msg: InterruptionMessage, claim) -> None:
        """interruption/events parity: surface what hit the node. Only
        actionable kinds reach here (the caller returns early otherwise),
        and every one of them ends in cordon-and-drain."""
        if self.recorder is None:
            return
        from ..utils import events as ev
        name = claim.metadata.name
        if msg.kind == "spot_interruption":
            ev.spot_interrupted(self.recorder, name)
        elif msg.kind == "rebalance_recommendation":
            ev.rebalance_recommendation(self.recorder, name)
        elif msg.kind == "state_change":
            ev.instance_stopping(self.recorder, name)
        ev.terminating_on_interruption(self.recorder, name)


class CatalogController:
    """12h instance-type + offerings refresh (controller.go:43-60).

    Offering prices come from the PricingProvider when one is wired —
    that is where the static-fallback / last-known-good semantics live
    (pricing.go:108-157): a dead pricing API must not leave the catalog
    unpriced, and the catalog must never bypass the fallback by reading
    the raw cloud API (the reference's instancetype resolver reads
    pricing.OnDemandPrice/SpotPrice the same way, types.go:120-157)."""

    def __init__(self, ec2, provider: InstanceTypeProvider, metrics=None,
                 unavailable_offerings=None, pricing=None):
        self.ec2 = ec2
        self.provider = provider
        self.metrics = metrics
        self.unavailable = unavailable_offerings
        self.pricing = pricing

    def reconcile(self) -> bool:
        infos = self.ec2.describe_instance_types()
        changed = self.provider.update_instance_types(infos)
        type_zones: Dict[str, set] = {}
        for t, z in self.ec2.describe_instance_type_offerings():
            type_zones.setdefault(t, set()).add(z)
        if self.pricing is not None:
            od = self.pricing.on_demand_prices()
            spot = {}
            for t, zs in type_zones.items():
                for z in zs:
                    p = self.pricing.spot_price(t, z)
                    if p is not None:
                        spot[(t, z)] = p
        else:  # no pricing provider wired (bare test harnesses)
            od = self.ec2.on_demand_prices()
            spot = {(t, z): p
                    for t, z, p in self.ec2.describe_spot_price_history()}
        changed |= self.provider.update_offerings(OfferingsSnapshot(
            zones={z.name: z for z in self.ec2.zones},
            type_zones=type_zones,
            od_prices=od,
            spot_prices=spot,
        ))
        if self.metrics is not None:
            # unconditionally: availability also tracks the 3m-TTL ICE
            # blacklist, which moves far more often than the catalog
            self._emit_gauges(infos, type_zones, od, spot)
            self._gauge_inputs = (infos, type_zones, od, spot)
        return changed

    def refresh_gauges(self) -> None:
        """Re-sample offering availability against the current ICE
        blacklist without a catalog sweep (the daemon runs this at a
        short cadence so the gauge tracks the 3m blacklist TTL)."""
        inputs = getattr(self, "_gauge_inputs", None)
        if inputs is not None and self.metrics is not None:
            self._emit_gauges(*inputs)

    def _emit_gauges(self, infos, type_zones, od, spot) -> None:
        """Provider-side gauges (instancetype/metrics.go,
        metrics.md offering availability/price): per-type cpu/memory and
        per-offering availability + price estimate. Full re-emit: series
        for types/offerings that left the catalog must not linger."""
        m = self.metrics
        for series in ("karpenter_cloudprovider_instance_type",
                       "karpenter_cloudprovider_instance_type_cpu_cores",
                       "karpenter_cloudprovider_instance_type_memory_bytes",
                       "karpenter_cloudprovider_instance_type"
                       "_offering_available",
                       "karpenter_cloudprovider_instance_type"
                       "_offering_price_estimate"):
            m.clear_series(series)

        def available(ct, itype, zone):
            if self.unavailable is not None                     and self.unavailable.is_unavailable(ct, itype, zone):
                return 0.0  # ICE-blacklisted pool (solver input, 3m TTL)
            return 1.0

        for info in infos:
            m.set_gauge("karpenter_cloudprovider_instance_type", 1.0,
                        labels={"instance_type": info.name})
            m.set_gauge("karpenter_cloudprovider_instance_type_cpu_cores",
                        float(info.vcpus),
                        labels={"instance_type": info.name})
            m.set_gauge("karpenter_cloudprovider_instance_type_memory_bytes",
                        float(info.memory_bytes),
                        labels={"instance_type": info.name})
            for z in type_zones.get(info.name, ()):  
                m.set_gauge(
                    "karpenter_cloudprovider_instance_type_offering_available",
                    available("on-demand", info.name, z),
                    labels={"instance_type": info.name, "zone": z,
                            "capacity_type": "on-demand"})
                m.set_gauge(
                    "karpenter_cloudprovider_instance_type_offering_price_estimate",
                    od.get(info.name, 0) / 1e6,
                    labels={"instance_type": info.name, "zone": z,
                            "capacity_type": "on-demand"})
                sp = spot.get((info.name, z))
                if sp is not None:
                    m.set_gauge(
                        "karpenter_cloudprovider_instance_type_offering_available",
                        available("spot", info.name, z),
                        labels={"instance_type": info.name, "zone": z,
                                "capacity_type": "spot"})
                    m.set_gauge(
                        "karpenter_cloudprovider_instance_type_offering_price_estimate",
                        sp / 1e6,
                        labels={"instance_type": info.name, "zone": z,
                                "capacity_type": "spot"})


class PricingController:
    def __init__(self, pricing: PricingProvider):
        self.pricing = pricing

    def reconcile(self) -> bool:
        a = self.pricing.update_on_demand_pricing()
        b = self.pricing.update_spot_pricing()
        return a or b


class StaticHashController:
    """Re-stamp NodeClaim hash annotations when the hash VERSION bumps
    (nodeclass/hash/controller.go:41-47): a framework upgrade that changes
    how the static-field hash is computed must not report every node as
    drifted — claims on the old version get the freshly computed hash and
    the new version stamped, so only real spec changes drift."""

    def __init__(self, kube: FakeKube):
        self.kube = kube

    def reconcile(self) -> int:
        n = 0
        nodeclasses = {nc.metadata.name: nc
                       for nc in self.kube.list("EC2NodeClass")}
        nodepools = {np.metadata.name: np
                     for np in self.kube.list("NodePool")}
        for claim in self.kube.list("NodeClaim"):
            ann = claim.metadata.annotations
            changed = False
            if ann.get(L.EC2NODECLASS_HASH_VERSION_ANNOTATION) \
                    != L.EC2NODECLASS_HASH_VERSION:
                nc = nodeclasses.get(claim.node_class_ref.name)
                if nc is not None:
                    ann[L.EC2NODECLASS_HASH_ANNOTATION] = nc.hash()
                    ann[L.EC2NODECLASS_HASH_VERSION_ANNOTATION] = \
                        L.EC2NODECLASS_HASH_VERSION
                    changed = True
            # same upgrade-safety for the NODEPOOL static hash (core's
            # nodepool-hash migration): a version bump restamps, so only
            # real spec changes drift
            if ann.get(L.NODEPOOL_HASH_VERSION_ANNOTATION) \
                    != L.NODEPOOL_HASH_VERSION:
                np = nodepools.get(claim.nodepool or "")
                if np is not None:
                    ann[L.NODEPOOL_HASH_ANNOTATION] = np.hash()
                    ann[L.NODEPOOL_HASH_VERSION_ANNOTATION] = \
                        L.NODEPOOL_HASH_VERSION
                    changed = True
            if changed:
                self.kube.update(claim)
                n += 1
        return n


class DiscoveredCapacityController:
    """Teach the catalog real memory from live nodes
    (providers/instancetype/capacity/controller.go:54-73): the first node
    of each (instance type, AMI) reports its true capacity, which the
    instance-type provider then prefers over the vm-overhead estimate for
    future solves (60-day cache)."""

    def __init__(self, kube: FakeKube, instance_types: InstanceTypeProvider):
        self.kube = kube
        self.instance_types = instance_types
        self._seen: Set[str] = set()

    def reconcile(self) -> int:
        n = 0
        claims = {c.metadata.name: c for c in self.kube.list("NodeClaim")}
        for node in self.kube.list("Node"):
            name = node.metadata.name
            if not node.ready or name in self._seen:
                continue
            itype = node.metadata.labels.get(L.INSTANCE_TYPE, "")
            claim = claims.get(name)
            ami = claim.image_id if claim is not None else ""
            mem = node.capacity["memory"]
            if itype and mem:
                self.instance_types.update_discovered_capacity(
                    itype, ami, int(mem))
                self._seen.add(name)
                n += 1
        return n


class SSMInvalidationController:
    """Every 30m, evict mutable SSM entries whose AMIs were deprecated
    (ssm/invalidation/controller.go:55-88) so the next AMI resolve sees
    the replacement image instead of a poisoned cache."""

    INTERVAL = 30 * 60.0

    def __init__(self, ec2, ami_provider: AMIProvider, ssm=None,
                 clock=time.time):
        self.ec2 = ec2
        self.ami = ami_provider
        self.ssm = ssm
        self.clock = clock
        self._last = 0.0

    def reconcile(self, force: bool = False) -> int:
        now = self.clock()
        if not force and now - self._last < self.INTERVAL:
            return 0
        self._last = now
        evicted = self.ami.invalidate_deprecated()
        if self.ssm is not None:
            deprecated = {img.id for img in self.ec2.describe_images()
                          if img.deprecated}
            evicted += self.ssm.invalidate_deprecated(deprecated)
        return evicted


class VersionController:
    """Periodic kubernetes-version refresh with validation
    (providers/version/controller.go:45-53). The source callable stands in
    for EKS DescribeCluster / the /version endpoint."""

    def __init__(self, provider, source, clock=time.time,
                 interval: float = 5 * 60.0):
        self.provider = provider
        self.source = source
        self.clock = clock
        self.interval = interval
        self._last = 0.0

    def reconcile(self, force: bool = False) -> bool:
        now = self.clock()
        if not force and now - self._last < self.interval:
            return False
        self._last = now
        return self.provider.update(self.source())
