from .lifecycle import NodeClaimLifecycle, Terminator
from .provisioning import Provisioner, ProvisioningResult
from .steady_state import (CatalogController, GarbageCollector,
                           InterruptionController, NodeClassStatusController,
                           PricingController, Tagger)

__all__ = ["Provisioner", "ProvisioningResult", "NodeClaimLifecycle",
           "Terminator", "NodeClassStatusController", "GarbageCollector",
           "Tagger", "InterruptionController", "CatalogController",
           "PricingController"]
