"""Provisioning controller: pending pods -> Solver -> NodeClaims.

The core ``provisioning.Provisioner`` (SURVEY §3.2): batch pending pods,
build the scheduling snapshot (nodepool specs with resolved instance types,
existing capacity from cluster state, daemonset overheads), run the
pluggable Solver, create NodeClaim CRs, and nominate pods to their planned
nodes so the next round doesn't double-provision.
"""

from __future__ import annotations

import itertools
import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Sequence

from ..apis import labels as L
from ..apis.objects import NodeClaim, NodePool, Pod, resolve_pod_priorities
from ..apis.requirements import Requirements
from ..apis.resources import Resources
from ..cloudprovider.provider import CloudProvider
from ..fake.kube import FakeKube
from ..solver.types import (DaemonOverhead, NewNodeClaim, NodePoolSpec,
                            SchedulingSnapshot, Solver, SolveResult)
from ..state.cluster import ClusterState

log = logging.getLogger(__name__)
_claim_seq = itertools.count(1)


@dataclass
class ProvisioningResult:
    created_claims: List[NodeClaim] = field(default_factory=list)
    nominated: Dict[str, str] = field(default_factory=dict)
    unschedulable: Dict[str, str] = field(default_factory=dict)
    solve_duration_s: float = 0.0
    #: victim full_name -> node it was evicted from (preemption applied
    #: this round); empty when no search ran or the verdict was negative
    preempted: Dict[str, str] = field(default_factory=dict)
    #: the round's PreemptionVerdict (None = search not consulted)
    preempt: object = None


class Provisioner:
    def __init__(self, kube: FakeKube, state: ClusterState,
                 cloudprovider: CloudProvider, solver: Solver,
                 metrics=None, clock=time.time,
                 batch_window_s: float = 0.0,
                 preempt_planner=None):
        self.kube = kube
        self.state = state
        self.cloudprovider = cloudprovider
        self.solver = solver
        self.metrics = metrics
        self.clock = clock
        #: optional scheduling.PreemptionPlanner — consulted when a
        #: solve leaves priority-bearing pods unschedulable, BEFORE the
        #: round gives up on them (None = preemption disabled)
        self.preempt_planner = preempt_planner
        # batching window (core batchIdleDuration): pods arriving within
        # the window ride the same solve. With a delta-capable solver the
        # window isn't dead time — we hand it the snapshot up front so it
        # can encode/pack speculatively while we wait for stragglers.
        self.batch_window_s = batch_window_s

    def reconcile(self) -> ProvisioningResult:
        """One provisioning round (core Provisioner.Schedule)."""
        pods = self.state.pending_pods()
        result = ProvisioningResult()
        if self.metrics is not None:
            # scheduler queue depth = pending pods entering this round
            # (metrics.md:191-197)
            self.metrics.set_gauge("karpenter_scheduler_queue_depth",
                                   float(len(pods)))
        if not pods:
            return result
        # pods whose PVCs don't exist yet are held out of the solve
        # (volumetopology.go errors and skips the pod: scheduling before
        # the claim materializes could pin it to the wrong zone)
        held = self._pods_awaiting_claims(pods)
        if held:
            for p in held:
                result.unschedulable[p.full_name()] = \
                    "awaiting PersistentVolumeClaim creation"
            pods = [p for p in pods if p.full_name()
                    not in result.unschedulable]
            if not pods:
                return result
        snapshot = self.build_snapshot(pods)
        if self.batch_window_s > 0 and hasattr(self.solver, "speculate"):
            # speculative pre-encode: the solver starts its delta-encoder
            # walk against the provisional snapshot while the batch window
            # soaks up stragglers. If the pod set didn't move, the solve
            # below consumes the finished prep (same snapshot object); if
            # it did, we rebuild and the solver discards the speculation
            # via its state-token check — never a stale solve.
            self.solver.speculate(snapshot)
            time.sleep(self.batch_window_s)
            fresh = self.state.pending_pods()
            for p in self._pods_awaiting_claims(fresh):
                result.unschedulable.setdefault(
                    p.full_name(), "awaiting PersistentVolumeClaim creation")
            fresh = [p for p in fresh
                     if p.full_name() not in result.unschedulable]
            if {p.full_name() for p in fresh} != \
                    {p.full_name() for p in pods}:
                pods = fresh
                snapshot = self.build_snapshot(pods)
        t0 = time.perf_counter()
        solved = self.solver.solve(snapshot)
        result.solve_duration_s = time.perf_counter() - t0
        if self.metrics is not None:
            self.metrics.observe("karpenter_scheduler_scheduling_duration_seconds",
                                 result.solve_duration_s)
        result.unschedulable.update(solved.unschedulable)

        pods_by_name = {p.full_name(): p for p in pods}
        # pods onto existing capacity -> nominate
        for pod_name, node_name in solved.existing_assignments.items():
            self.state.nominate(pod_name, node_name)
            result.nominated[pod_name] = node_name
        # new nodes -> NodeClaim CRs
        for plan in solved.new_nodes:
            claim = self._create_nodeclaim(plan, pods_by_name)
            result.created_claims.append(claim)
            for pod_name in plan.pod_names:
                self.state.nominate(pod_name, claim.name)
                result.nominated[pod_name] = claim.name
        # leftovers with priority: consult the preemption search before
        # the round gives up on them
        if result.unschedulable and self.preempt_planner is not None:
            self._maybe_preempt(snapshot, result)
        return result

    def _maybe_preempt(self, snapshot: SchedulingSnapshot,
                       result: ProvisioningResult) -> None:
        """Preemption verdict -> applied Command: evict the victim
        prefix (unbind — the pods requeue at their own priority next
        round), re-solve ONLY the unblocked demand against the refunded
        capacity, and nominate the assignments. The planner guarantees
        existing-capacity placement; the solve stays the authority that
        picks nodes."""
        verdict = self.preempt_planner.plan(
            snapshot, list(result.unschedulable), self.state)
        result.preempt = verdict
        if not verdict.feasible:
            return
        for pod in verdict.victims:
            result.preempted[pod.full_name()] = pod.node_name
            self.state.clear_nomination(pod.full_name())
            pod.node_name = ""
            pod.phase = "Pending"
            self.kube.update(pod)
        demand = list(verdict.demand)
        solved = self.solver.solve(self.build_snapshot(demand))
        for pod_name, node_name in solved.existing_assignments.items():
            self.state.nominate(pod_name, node_name)
            result.nominated[pod_name] = node_name
            result.unschedulable.pop(pod_name, None)
        if solved.new_nodes:
            # contradicts the verdict's zero-new-nodes guarantee (only
            # reachable if the cluster moved between plan and re-solve):
            # never mint off a preemption round — the pods stay pending
            # and the next reconcile handles them with fresh state
            log.warning(
                "preemption re-solve wanted %d new node(s); ignoring "
                "(verdict promised existing capacity only)",
                len(solved.new_nodes))

    def _pods_awaiting_claims(self, pods: Sequence[Pod]) -> List[Pod]:
        """Pods referencing a PVC that doesn't exist (yet)."""
        out = []
        for pod in pods:
            for claim_name in getattr(pod, "volume_claims", ()) or ():
                if self.kube.try_get("PersistentVolumeClaim", claim_name,
                                     namespace=pod.metadata.namespace) is None:
                    out.append(pod)
                    break
        return out

    def _resolve_volume_topology(self, pods: Sequence[Pod]) -> None:
        """Core scheduling/volumetopology.go: pods mounting PVCs inherit
        zone constraints from their bound PV's node affinity (or the
        StorageClass's allowedTopologies for unbound claims), and consume
        one EBS attachment slot per claim (CSINode limit accounting)."""
        from ..apis.requirements import IN, Requirement, Requirements
        for pod in pods:
            claims = getattr(pod, "volume_claims", None)
            ephemeral = getattr(pod, "ephemeral_volumes", None)
            if not claims and not ephemeral:
                continue
            terms = []
            n_volumes = 0

            def _claim_constraints(pvc, fallback_class=""):
                """One claim's zone terms (bound PV wins; else the
                class's allowedTopologies)."""
                if pvc is not None and pvc.bound:
                    pv = self.kube.try_get("PersistentVolume",
                                           pvc.volume_name)
                    if pv is not None and pv.zone:
                        terms.append(Requirement.new(L.ZONE, IN, [pv.zone]))
                    return
                sc_name = pvc.storage_class if pvc is not None \
                    else fallback_class
                sc = self.kube.try_get("StorageClass", sc_name) \
                    if sc_name else None
                if sc is not None and sc.allowed_topology_zones:
                    terms.append(Requirement.new(
                        L.ZONE, IN, list(sc.allowed_topology_zones)))

            for claim_name in claims or ():
                pvc = self.kube.try_get("PersistentVolumeClaim", claim_name,
                                        namespace=pod.metadata.namespace)
                if pvc is None:
                    continue
                n_volumes += 1
                _claim_constraints(pvc)
            # generic ephemeral volumes: the PVC (`<pod>-<volume>`) is
            # created by the kubelet at bind time, so an absent PVC does
            # NOT skip the volume — it still takes an attachment slot and
            # its class's allowed topologies apply (core
            # volumetopology.go treats the templated claim the same way)
            for vol_name, sc_name in ephemeral or ():
                n_volumes += 1
                pvc = self.kube.try_get(
                    "PersistentVolumeClaim",
                    f"{pod.metadata.name}-{vol_name}",
                    namespace=pod.metadata.namespace)
                _claim_constraints(pvc, fallback_class=sc_name)
            pod.apply_volume_constraints(Requirements(terms), n_volumes)

    def build_snapshot(self, pods: Sequence[Pod]) -> SchedulingSnapshot:
        self._resolve_volume_topology(pods)
        # resolve priorityClassName -> numeric priority against the live
        # PriorityClass table (unconditional: a deleted class must reset
        # its pods to the default). With no PriorityClass objects every
        # pod stays at 0 and the solve is byte-identical to a
        # priority-free build (tests/test_preempt.py fingerprint gate).
        priority_classes = self.kube.list("PriorityClass")
        resolve_pod_priorities(pods, priority_classes)
        usage = self.state.nodepool_usage()
        specs: List[NodePoolSpec] = []
        for np in self.kube.list("NodePool"):
            try:
                types = self.cloudprovider.get_instance_types(np)
            except Exception as e:  # NodeClass missing/not ready
                log.warning("nodepool %s skipped: %s", np.name, e)
                continue
            if not types:
                continue
            specs.append(NodePoolSpec(
                nodepool=np, instance_types=types,
                in_use=usage.get(np.name, Resources())))
        daemons = self._daemon_overheads()
        zones = {}
        for spec in specs:
            for it in spec.instance_types:
                for o in it.offerings:
                    zones.setdefault(o.zone, o.zone_id)
        return SchedulingSnapshot(
            pods=list(pods), nodepools=specs,
            existing_nodes=self.state.existing_nodes(),
            daemon_overheads=daemons, zones=zones,
            priority_classes=priority_classes)

    def _daemon_overheads(self) -> List[DaemonOverhead]:
        """Daemonset pods: every new node admitting them pays their requests."""
        out = []
        for pod in self.kube.list("Pod"):
            if pod.owner_kind == "DaemonSet":
                out.append(DaemonOverhead(
                    requests=pod.effective_requests(),
                    requirements=pod.scheduling_requirements()))
        return out

    def _create_nodeclaim(self, plan: NewNodeClaim,
                          pods_by_name: Dict[str, Pod]) -> NodeClaim:
        nodepool = self.kube.get("NodePool", plan.nodepool)
        labels = dict(nodepool.template.labels)
        labels[L.NODEPOOL] = plan.nodepool
        # single-valued requirements become labels (core nodeclaim template)
        for k, v in plan.requirements.single_values().items():
            labels.setdefault(k, v)
        claim = NodeClaim(
            name=f"{plan.nodepool}-{next(_claim_seq):05d}",
            requirements=plan.requirements,
            node_class_ref=nodepool.template.node_class_ref,
            resources_requested=plan.requests,
            taints=plan.taints,
            startup_taints=nodepool.template.startup_taints,
            labels=labels,
            annotations={
                # user template annotations ride onto the claim (and the
                # node via the kubelet's registration)
                **nodepool.template.annotations,
                L.NODEPOOL_HASH_ANNOTATION: nodepool.hash(),
                L.NODEPOOL_HASH_VERSION_ANNOTATION: L.NODEPOOL_HASH_VERSION,
            },
            expire_after=nodepool.template.expire_after,
            termination_grace_period=(
                nodepool.template.termination_grace_period))
        claim.metadata.finalizers.append("karpenter.sh/termination")
        claim.instance_type_options = list(plan.instance_type_names)
        self.kube.create(claim)
        if self.metrics is not None:
            self.metrics.inc("karpenter_nodeclaims_created_total",
                             labels={"nodepool": plan.nodepool})
        return claim
