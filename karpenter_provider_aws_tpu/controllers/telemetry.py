"""Fleet-operations telemetry: the metric families the reference documents
beyond the solver hot path (website/content/en/docs/reference/metrics.md,
101 series in 20 groups).

Three pieces:

- :class:`TelemetryEmitter` — a periodic reconciler that walks cluster
  state and re-emits the gauge families (nodes/pods/cluster-state/
  nodepools) plus the operatorpkg-style status-condition and termination
  series for every karpenter kind;
- :func:`instrument_kube` — wraps the kube boundary with the
  ``client_go_request_*`` series (client-go's rest_client metrics);
- :func:`instrument_ec2` — wraps the fake AWS seam with the
  ``aws_sdk_go_request_*`` series (the prometheusv2-wrapped AWS config of
  operator.go:110).

Counters for one-shot events (created/terminated/drained/evicted,
interruption deletions, disruption failures) are emitted at their source
controllers; this module owns only the walk-the-world families.
"""

from __future__ import annotations

import time
from typing import Dict, Tuple

from ..apis import labels as L
from ..apis.resources import Resources

#: kinds that get operatorpkg status-condition + termination series
#: (metrics.md operator_{kind}_* groups)
_KINDS = (("NodeClaim", "nodeclaim"), ("Node", "node"),
          ("NodePool", "nodepool"), ("EC2NodeClass", "ec2nodeclass"))

_RESOURCES = ("cpu", "memory")


class TelemetryEmitter:
    """Walks kube state once per reconcile and re-emits every
    walk-the-world gauge family. Transition counters keep a previous-state
    map so `*_transitions_total` / `*_transition_seconds` match the
    operatorpkg semantics (count + duration of the status being left)."""

    def __init__(self, kube, state, metrics, clock=time.time):
        self.kube = kube
        self.state = state
        self.metrics = metrics
        self.clock = clock
        #: (kind, name, ctype) -> (status, since)
        self._cond_prev: Dict[Tuple[str, str, str], Tuple[str, float]] = {}
        #: (kind, name) -> deletion timestamp of objects seen deleting
        self._deleting: Dict[Tuple[str, str], float] = {}

    # ------------------------------------------------------------------
    def reconcile(self) -> int:
        now = self.clock()
        m = self.metrics
        nodes = self.kube.list("Node")
        claims = self.kube.list("NodeClaim")
        pools = self.kube.list("NodePool")
        pods = self.kube.list("Pod")

        self._emit_nodes(nodes, claims, pods, now)
        self._emit_pods(pods, now)
        self._emit_cluster(nodes, pods)
        self._emit_nodepools(pools, claims)
        for kind, prefix in _KINDS:
            objs = self.kube.list(kind)
            self._emit_conditions(kind, prefix, objs, now)
            self._emit_termination(kind, prefix, objs, now)
        return 1

    # -- nodes family ---------------------------------------------------
    def _emit_nodes(self, nodes, claims, pods, now) -> None:
        m = self.metrics
        claim_by_node = {c.node_name: c for c in claims if c.node_name}
        by_node: Dict[str, list] = {}
        for p in pods:
            if p.node_name and p.phase not in ("Succeeded", "Failed"):
                by_node.setdefault(p.node_name, []).append(p)
        for name in ("karpenter_nodes_allocatable",
                     "karpenter_nodes_total_pod_requests",
                     "karpenter_nodes_total_pod_limits",
                     "karpenter_nodes_total_daemon_requests",
                     "karpenter_nodes_total_daemon_limits",
                     "karpenter_nodes_system_overhead",
                     "karpenter_nodes_current_lifetime_seconds"):
            m.clear_series(name)
        for node in nodes:
            claim = claim_by_node.get(node.metadata.name)
            pool = node.metadata.labels.get(L.NODEPOOL, "")
            base = {"node_name": node.metadata.name, "nodepool": pool}
            reqs = Resources()
            lims = Resources()
            dreqs = Resources()
            dlims = Resources()
            for p in by_node.get(node.metadata.name, []):
                r = p.effective_requests()
                lim = getattr(p, "limits", None) or Resources()
                if p.owner_kind == "DaemonSet":
                    dreqs = dreqs + r
                    dlims = dlims + lim
                else:
                    reqs = reqs + r
                    lims = lims + lim
            overhead = (node.capacity - node.allocatable).clamp_nonnegative()
            for res in _RESOURCES:
                lab = dict(base, resource_type=res)
                m.set_gauge("karpenter_nodes_allocatable",
                            node.allocatable[res], labels=lab)
                m.set_gauge("karpenter_nodes_total_pod_requests",
                            reqs[res], labels=lab)
                m.set_gauge("karpenter_nodes_total_pod_limits",
                            lims[res], labels=lab)
                m.set_gauge("karpenter_nodes_total_daemon_requests",
                            dreqs[res], labels=lab)
                m.set_gauge("karpenter_nodes_total_daemon_limits",
                            dlims[res], labels=lab)
                m.set_gauge("karpenter_nodes_system_overhead",
                            overhead[res], labels=lab)
            m.set_gauge("karpenter_nodes_current_lifetime_seconds",
                        max(0.0, now - node.metadata.creation_timestamp),
                        labels=base)

    # -- pods ------------------------------------------------------------
    def _emit_pods(self, pods, now) -> None:
        m = self.metrics
        m.clear_series("karpenter_pods_state")
        counts: Dict[str, int] = {}
        ignored = 0
        for p in pods:
            counts[p.phase] = counts.get(p.phase, 0) + 1
            # a pending pod the provisioner cannot act on (already being
            # deleted) is ignored, the metrics.md ignored_pod_count shape
            if p.phase == "Pending" and not p.node_name \
                    and p.metadata.deletion_timestamp is not None:
                ignored += 1
        for phase, n in counts.items():
            m.set_gauge("karpenter_pods_state", n, labels={"phase": phase})
        m.set_gauge("karpenter_ignored_pod_count", ignored)

    # -- cluster state ---------------------------------------------------
    def _emit_cluster(self, nodes, pods) -> None:
        m = self.metrics
        total_alloc = Resources()
        for node in nodes:
            total_alloc = total_alloc + node.allocatable
        total_req = Resources()
        for p in pods:
            if p.node_name and p.phase not in ("Succeeded", "Failed"):
                total_req = total_req + p.effective_requests()
        for res in _RESOURCES:
            alloc = total_alloc[res]
            m.set_gauge("karpenter_cluster_utilization_percent",
                        100.0 * total_req[res] / alloc if alloc else 0.0,
                        labels={"resource_type": res})

    # -- nodepools -------------------------------------------------------
    def _emit_nodepools(self, pools, claims) -> None:
        m = self.metrics
        for name in ("karpenter_nodepools_limit",
                     "karpenter_nodepools_allowed_disruptions"):
            m.clear_series(name)
        by_pool: Dict[str, int] = {}
        for c in claims:
            if c.registered:
                by_pool[c.nodepool or ""] = by_pool.get(c.nodepool or "", 0) + 1
        for pool in pools:
            if pool.limits:
                for res, lim in pool.limits.items():
                    m.set_gauge("karpenter_nodepools_limit", lim,
                                labels={"nodepool": pool.name,
                                        "resource_type": res})
            total = by_pool.get(pool.name, 0)
            allowed = total
            for b in pool.disruption.budgets:
                allowed = min(allowed, b.max_disruptions(total))
            m.set_gauge("karpenter_nodepools_allowed_disruptions", allowed,
                        labels={"nodepool": pool.name})

    # -- operatorpkg status conditions ----------------------------------
    def _emit_conditions(self, kind, prefix, objs, now) -> None:
        m = self.metrics
        m.clear_series(f"operator_{prefix}_status_condition_count")
        m.clear_series(
            f"operator_{prefix}_status_condition_current_status_seconds")
        live = set()
        for obj in objs:
            for cond in getattr(obj, "conditions", {}).values():
                key = (kind, obj.metadata.name, cond.type)
                live.add(key)
                lab = {"type": cond.type, "status": cond.status}
                m.set_gauge(f"operator_{prefix}_status_condition_count",
                            m.gauge(
                                f"operator_{prefix}_status_condition_count",
                                labels=lab) + 1, labels=lab)
                prev = self._cond_prev.get(key)
                if prev is None:
                    self._cond_prev[key] = (cond.status,
                                            cond.last_transition)
                elif prev[0] != cond.status:
                    m.inc(f"operator_{prefix}"
                          "_status_condition_transitions_total",
                          labels={"type": cond.type, "from": prev[0],
                                  "to": cond.status})
                    m.observe(f"operator_{prefix}"
                              "_status_condition_transition_seconds",
                              max(0.0, now - prev[1]),
                              labels={"type": cond.type})
                    # the generic operatorpkg group (metrics.md
                    # operator_status_condition_*) aggregates every kind
                    m.inc("operator_status_condition_transitions_total",
                          labels={"kind": kind, "type": cond.type})
                    m.observe("operator_status_condition_transition_seconds",
                              max(0.0, now - prev[1]),
                              labels={"kind": kind, "type": cond.type})
                    self._cond_prev[key] = (cond.status, now)
                m.set_gauge(
                    f"operator_{prefix}_status_condition"
                    "_current_status_seconds",
                    max(0.0, now - self._cond_prev[key][1]),
                    labels={"name": obj.metadata.name, "type": cond.type})
        # aggregated per-kind counts for the generic group
        m.clear_series("operator_status_condition_count",
                       match={"kind": kind})
        per: Dict[Tuple[str, str], int] = {}
        for obj in objs:
            for cond in getattr(obj, "conditions", {}).values():
                k = (cond.type, cond.status)
                per[k] = per.get(k, 0) + 1
        for (ctype, status), n in per.items():
            m.set_gauge("operator_status_condition_count", n,
                        labels={"kind": kind, "type": ctype,
                                "status": status})
        m.set_gauge("operator_status_condition_current_status_seconds",
                    float(len(live)), labels={"kind": kind})
        # drop transition state for vanished objects
        for key in [k for k in self._cond_prev
                    if k[0] == kind and k not in live]:
            del self._cond_prev[key]

    # -- operatorpkg termination ----------------------------------------
    def _emit_termination(self, kind, prefix, objs, now) -> None:
        m = self.metrics
        m.clear_series(
            f"operator_{prefix}_termination_current_time_seconds")
        seen = set()
        for obj in objs:
            dt = obj.metadata.deletion_timestamp
            if dt is None:
                continue
            key = (kind, obj.metadata.name)
            seen.add(key)
            self._deleting.setdefault(key, dt)
            m.set_gauge(
                f"operator_{prefix}_termination_current_time_seconds",
                max(0.0, now - dt), labels={"name": obj.metadata.name})
        for key in [k for k in self._deleting
                    if k[0] == kind and k not in seen]:
            dt = self._deleting.pop(key)
            m.observe(f"operator_{prefix}_termination_duration_seconds",
                      max(0.0, now - dt))
            m.observe("operator_termination_duration_seconds",
                      max(0.0, now - dt), labels={"kind": kind})
        m.set_gauge("operator_termination_current_time_seconds",
                    float(sum(1 for k in self._deleting if k[0] == kind)),
                    labels={"kind": kind})


# ---------------------------------------------------------------------------
# boundary instrumentation
# ---------------------------------------------------------------------------

def instrument_kube(kube, metrics, clock=time.perf_counter) -> None:
    """client_go_request_total/_duration_seconds at the kube boundary —
    the rest_client metrics of metrics.md's Client Go group. Wraps the
    five verbs in place; labels mirror client-go (verb, code)."""
    for verb, method in (("GET", "get"), ("LIST", "list"),
                         ("POST", "create"), ("PUT", "update"),
                         ("DELETE", "delete")):
        orig = getattr(kube, method)

        def wrapped(*a, _orig=orig, _verb=verb, **kw):
            t0 = clock()
            code = "200"
            try:
                return _orig(*a, **kw)
            except Exception:
                code = "error"
                raise
            finally:
                metrics.inc("client_go_request_total",
                            labels={"verb": _verb, "code": code})
                metrics.observe("client_go_request_duration_seconds",
                                clock() - t0, labels={"verb": _verb})

        setattr(kube, method, wrapped)


#: fake-EC2 methods that stand in for SDK operations (pkg/aws/sdk.go seam)
_EC2_OPS = ("describe_instance_types", "describe_instance_type_offerings",
            "describe_spot_price_history", "describe_subnets",
            "describe_security_groups", "describe_images",
            "describe_launch_templates", "create_fleet",
            "describe_instances", "terminate_instances")


def instrument_sidecar(solver, metrics) -> None:
    """karpenter_solver_sidecar_* at the solver wire — attaches the
    registry to a RemoteSolver's resilience policy (retry counts,
    breaker transitions + state gauge, per-RPC outcomes) and to the
    solver itself (degraded-solve counter). Call it where the operator
    wires its other boundaries; safe no-op on a local solver without a
    wire client."""
    solver.metrics = metrics
    policy = getattr(getattr(solver, "client", None), "policy", None)
    if policy is not None:
        policy.metrics = metrics
        policy.emit_state()


def instrument_ec2(ec2, metrics, clock=time.perf_counter) -> None:
    """aws_sdk_go_request_* at the cloud seam — the prometheusv2-wrapped
    AWS config of operator.go:110. One attempt per call here (the fake
    has no transport retries); the LT-not-found application-level retry
    increments aws_sdk_go_request_retry_count at its site
    (providers/instance.py)."""
    for op in _EC2_OPS:
        orig = getattr(ec2, op, None)
        if orig is None:
            continue

        def wrapped(*a, _orig=orig, _op=op, **kw):
            t0 = clock()
            try:
                return _orig(*a, **kw)
            finally:
                dt = clock() - t0
                lab = {"service": "EC2", "operation": _op}
                metrics.inc("aws_sdk_go_request_total", labels=lab)
                metrics.observe("aws_sdk_go_request_duration_seconds",
                                dt, labels=lab)
                metrics.inc("aws_sdk_go_request_attempt_total", labels=lab)
                metrics.observe(
                    "aws_sdk_go_request_attempt_duration_seconds",
                    dt, labels=lab)

        setattr(ec2, op, wrapped)
