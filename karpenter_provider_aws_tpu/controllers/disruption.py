"""Disruption controller: drift, emptiness, consolidation, expiration.

Mirrors the core disruption controller the reference drives (SURVEY §3.5,
designs/consolidation.md):

- **Candidates** are initialized nodes ordered by ascending disruption cost
  (pods weighted by remaining lifetime, designs/consolidation.md:21-33);
  pods with the ``karpenter.sh/do-not-disrupt`` annotation block voluntary
  disruption of their node.
- **Graceful methods** run replacement-first: simulate scheduling of the
  candidate's pods against the remaining cluster (± replacement nodes),
  taint the candidates, launch replacements, and only terminate once every
  replacement is initialized.
- **Consolidation** = node deletion (pods fit on remaining capacity) or
  single-node replacement (remaining capacity + ONE cheaper node); the
  replacement catalog is price-filtered below the candidate's price so any
  solver answer is a strict saving. Spot→spot replacement additionally
  requires >=15 cheaper spot-capable types (flexibility floor, mirroring
  aws/karpenter-core's MinInstanceTypesForSpotToSpotConsolidation).
- **Multi-node consolidation** binary-searches the largest
  ascending-cost candidate prefix replaceable by <=1 cheaper node.
- **Expiration** is forceful (v1 semantics): expired NodeClaims are
  terminated without simulation and without budget gating.
- **Budgets** (NodePool.spec.disruption.budgets,
  crds/karpenter.sh_nodepools.yaml:78-141) cap concurrently-disrupting
  nodes per nodepool per reason.

The expensive inner loop — "can these pods be absorbed by the remaining
nodes?" per candidate — is delegated to a pluggable
:class:`ConsolidationEvaluator` so the TPU batched kernel
(ops/consolidation_jax.py) can pre-screen all candidates at once; decisions
remain identical to the sequential oracle (tests/test_disruption.py,
tests/test_consolidation_equivalence.py enforce it).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..apis import labels as L
from ..apis.objects import (DISRUPTED_TAINT, Node, NodeClaim, NodePool, Pod,
                            Taint)
from ..cloudprovider.provider import CloudProvider
from ..cloudprovider.types import InstanceTypes, NodeClaimNotFoundError
from ..fake.kube import FakeKube, NotFound
from ..solver.types import (
    NewNodeClaim,
    NodePoolSpec,
    SchedulingSnapshot,
    Solver,
    SolveResult)
from ..state.cluster import ClusterState

log = logging.getLogger(__name__)

DO_NOT_DISRUPT_ANNOTATION = L.DO_NOT_DISRUPT_ANNOTATION
POD_DELETION_COST_ANNOTATION = "controller.kubernetes.io/pod-deletion-cost"

#: spot→spot single-node replacement needs this much cheaper-type
#: flexibility, or consolidation would chase churn for pennies.
MIN_SPOT_FLEXIBILITY = 15

REASON_DRIFTED = "drifted"
REASON_EMPTY = "empty"
REASON_UNDERUTILIZED = "underutilized"
REASON_EXPIRED = "expired"

_GRACEFUL_ORDER = (REASON_DRIFTED, REASON_EMPTY, REASON_UNDERUTILIZED)


@dataclass
class Candidate:
    claim: NodeClaim
    node: Node
    nodepool: NodePool
    #: reschedulable (non-daemonset, non-terminal) pods bound to the node
    pods: List[Pod]
    #: current offering price, micro-USD/hour (0 if unknown)
    price: int
    disruption_cost: float
    capacity_type: str = ""
    instance_type: str = ""
    zone: str = ""
    blocked_by: str = ""  # non-empty => ineligible for voluntary disruption

    @property
    def name(self) -> str:
        return self.claim.name


@dataclass
class Command:
    reason: str
    candidates: List[Candidate]
    replacements: List[NewNodeClaim] = field(default_factory=list)

    def summary(self) -> str:
        return (f"{self.reason}: delete {[c.name for c in self.candidates]}"
                + (f" -> {len(self.replacements)} replacement(s)"
                   if self.replacements else ""))


@dataclass
class _InFlight:
    command: Command
    candidate_claims: List[str]
    replacement_claims: List[str]
    started: float


@dataclass
class ReplacementQuery:
    """One hypothetical disruption for the replacement pre-screen: the
    pods that would go pending, the node/claim names that would vanish,
    and the strict price bound on any replacement type."""
    pods: List[Pod]
    gone: Set[str]
    price_cap: int


@dataclass(frozen=True)
class SubsetVerdict:
    """One lane's answer from the device-native whole-fleet search
    (TPUConsolidationEvaluator.subset_solve): the EXACT outcome of the
    FFD re-solve of "cluster minus this subset" under the query's price
    cap. ``feasible`` (every pod absorbed) and ``n_new`` are decision
    gates — exact by the masking argument in docs/solver-design.md — so
    the controller walks the same candidates the sequential oracle
    would; ``flex``/``min_price``/``savings`` are the on-device
    spot-aware cost-delta evidence for the winning lane."""
    feasible: bool
    n_new: int
    flex: int = 0
    min_price: int = 0
    savings: int = 0


class ConsolidationEvaluator:
    """Answers "can these pods be absorbed by existing capacity alone?" for a
    batch of deletion candidates. The base implementation runs the solver
    sequentially (the oracle); the TPU evaluator batches all candidates into
    one device call."""

    def __init__(self, solver: Solver):
        self.solver = solver

    def deletions_feasible(
            self, snapshots: Sequence[SchedulingSnapshot]) -> List[bool]:
        out = []
        for snap in snapshots:
            res = self.solver.solve(snap)
            out.append(not res.new_nodes and not res.unschedulable)
        return out

    def replacements_prescreen(
            self, base: SchedulingSnapshot,
            queries: Sequence[ReplacementQuery]) -> List[bool]:
        """Exact-NO/maybe-YES per query: can the pods fit the surviving
        nodes plus at most one new node cheaper than the cap? False must
        be PROOF the replacement simulate would fail (the controller skips
        it); True means "run the authoritative simulate". The base
        implementation prunes nothing — the controller then behaves
        exactly like the sequential oracle."""
        return [True] * len(queries)

    def subset_solve(
            self, base: SchedulingSnapshot,
            queries: Sequence[ReplacementQuery],
    ) -> Optional[List[SubsetVerdict]]:
        """Whole-fleet device search: EXACT per-query verdicts for one
        stacked batch of "cluster minus subset" re-solves — unlike the
        prescreen, both False AND True are proofs, so the controller can
        replace its per-candidate solve loop with a walk over the
        verdicts (the authoritative simulate still mints the winning
        Command's launch specs). Returns None when the device path is
        unavailable or ineligible; the controller then falls back to the
        sequential oracle unchanged. The base implementation is
        host-only and always defers."""
        return None


class DisruptionController:
    def __init__(self, kube: FakeKube, state: ClusterState,
                 cloudprovider: CloudProvider, solver: Solver,
                 provisioner,  # controllers.provisioning.Provisioner
                 evaluator: Optional[ConsolidationEvaluator] = None,
                 metrics=None, clock=time.time,
                 consolidation_min_lifetime: float = 0.0,
                 consolidation_timeout: float = 60.0):
        self.kube = kube
        self.state = state
        self.cloudprovider = cloudprovider
        self.solver = solver
        self.provisioner = provisioner
        self.evaluator = evaluator or ConsolidationEvaluator(solver)
        self.metrics = metrics
        self.clock = clock
        self.consolidation_min_lifetime = consolidation_min_lifetime
        #: evaluation budget: an underutilized pass running longer than
        #: this counts a consolidation timeout (the reference aborts its
        #: search at a deadline; the batched kernel finishes the pass, so
        #: the metric marks budget overruns instead of truncations)
        self.consolidation_timeout = consolidation_timeout
        self._in_flight: List[_InFlight] = []
        #: claim name -> (frozenset of pod names, when it last changed);
        #: anchors consolidate_after to the last pod-set change
        self._pod_epoch: Dict[str, Tuple[frozenset, float]] = {}
        #: per-reconcile cached base snapshot (specs/existing/daemons/zones)
        self._round_base: Optional[SchedulingSnapshot] = None

    # ------------------------------------------------------------------
    # reconcile
    # ------------------------------------------------------------------
    def reconcile(self) -> Optional[Command]:
        """Progress in-flight commands; then issue at most ONE new command
        (the core loop also executes one command per pass)."""
        if self._progress_in_flight():
            # terminations just happened; candidate pods are still bound to
            # the dying nodes, so the replacement looks empty until the
            # drain + re-nomination settle — don't compute against that view
            self._expire()
            return None
        self._expire()  # forceful, not budgeted
        if self._in_flight:
            # replacement-first discipline: wait for in-flight replacements
            # before computing further voluntary disruption
            return None
        self._round_base = self.provisioner.build_snapshot([])
        candidates = self._build_candidates()
        if self.metrics is not None:
            self.metrics.set_gauge(
                "karpenter_voluntary_disruption_eligible_nodes",
                float(len([c for c in candidates if not c.blocked_by])))
        for reason in _GRACEFUL_ORDER:
            t0 = time.perf_counter()
            cmd = self._compute(reason, candidates)
            elapsed = time.perf_counter() - t0
            if self.metrics is not None:
                # metrics.md:181
                self.metrics.observe(
                    "karpenter_voluntary_disruption_decision_evaluation"
                    "_duration_seconds",
                    elapsed, labels={"method": reason})
                if reason == REASON_UNDERUTILIZED \
                        and elapsed > self.consolidation_timeout:
                    self.metrics.inc(
                        "karpenter_voluntary_disruption_consolidation"
                        "_timeouts_total")
            if cmd is not None:
                self._execute(cmd)
                return cmd
        return None

    # ------------------------------------------------------------------
    # candidate construction
    # ------------------------------------------------------------------
    def _build_candidates(self) -> List[Candidate]:
        pods_by_node: Dict[str, List[Pod]] = {}
        for pod in self.kube.list("Pod"):
            if pod.node_name and pod.phase not in ("Succeeded", "Failed"):
                pods_by_node.setdefault(pod.node_name, []).append(pod)
        nodepools = {np.name: np for np in self.kube.list("NodePool")}
        type_prices = self._price_index()
        now = self.clock()
        #: nodes that pods are nominated onto are off-limits — a nominated
        #: (in-flight) pod is invisible to pods_by_node, so the node would
        #: otherwise look empty and be consolidated out from under it
        nominated_nodes = self.state.nomination_targets()
        from .pdb import blocking_pdb, pdb_state
        pdbs = pdb_state(self.kube)

        out: List[Candidate] = []
        for claim in self.kube.list("NodeClaim"):
            if claim.metadata.deletion_timestamp is not None:
                continue
            if not (claim.registered and claim.initialized and claim.node_name):
                continue
            pool = nodepools.get(claim.nodepool or "")
            if pool is None:
                continue
            try:
                node = self.kube.get("Node", claim.node_name)
            except NotFound:
                continue
            if any(t.key == DISRUPTED_TAINT for t in node.taints):
                continue  # already being disrupted
            if node.name in nominated_nodes or claim.name in nominated_nodes:
                continue  # pods are in flight toward this node
            pods = [p for p in pods_by_node.get(node.name, [])
                    if p.owner_kind != "DaemonSet"]
            # a pod going Succeeded/Failed in place drops out of
            # pods_by_node (terminal pods are excluded there), so a
            # terminal transition registers as a membership change —
            # a pod event (consolidation suite_test.go:130)
            pod_set = frozenset(p.full_name() for p in pods)
            prev = self._pod_epoch.get(claim.name)
            if prev is None and claim.last_pod_event > 0:
                # operator restart: resume from the anchor persisted in
                # claim status (upstream's lastPodEventTime) instead of
                # restarting every stabilization window from zero
                prev = (pod_set, claim.last_pod_event)
                self._pod_epoch[claim.name] = prev
            if prev is None or prev[0] != pod_set:
                self._pod_epoch[claim.name] = (pod_set, now)
                claim.last_pod_event = now  # durable (state-in-cluster)
                self.kube.update(claim)
            blocked = ""
            # the annotation blocks disruption at every level: node,
            # claim, or any resident pod (core candidate filtering)
            if node.metadata.annotations.get(
                    DO_NOT_DISRUPT_ANNOTATION) == "true":
                blocked = f"node {node.name} has do-not-disrupt"
            elif claim.metadata.annotations.get(
                    DO_NOT_DISRUPT_ANNOTATION) == "true":
                blocked = f"nodeclaim {claim.name} has do-not-disrupt"
            else:
                for p in pods:
                    if p.metadata.annotations.get(
                            DO_NOT_DISRUPT_ANNOTATION) == "true":
                        blocked = f"pod {p.full_name()} has do-not-disrupt"
                        break
                    bp = blocking_pdb(pdbs, p)
                    if bp is not None:
                        blocked = (f"pod {p.full_name()} blocked by "
                                   f"pdb {bp.metadata.name}")
                        break
            itype = claim.metadata.labels.get(L.INSTANCE_TYPE, "")
            ct = claim.metadata.labels.get(L.CAPACITY_TYPE, "")
            zone = claim.metadata.labels.get(L.ZONE, "")
            out.append(Candidate(
                claim=claim, node=node, nodepool=pool, pods=pods,
                price=type_prices.get((pool.name, itype, ct, zone), 0),
                disruption_cost=self._disruption_cost(claim, pods, now),
                capacity_type=ct, instance_type=itype, zone=zone,
                blocked_by=blocked))
        # ascending disruption cost; stable deterministic tie-break
        out.sort(key=lambda c: (c.disruption_cost, c.name))
        return out

    def _price_index(self) -> Dict[Tuple[str, str, str, str], int]:
        """(nodepool, type, capacity_type, zone) -> current price, from the
        per-round base snapshot's already-resolved catalogs."""
        idx: Dict[Tuple[str, str, str, str], int] = {}
        for spec in self._round_base.nodepools:
            for it in spec.instance_types:
                for o in it.offerings:
                    idx[(spec.nodepool.name, it.name,
                         o.capacity_type, o.zone)] = o.price
        return idx

    def _disruption_cost(self, claim: NodeClaim, pods: Sequence[Pod],
                         now: float) -> float:
        """Pods weighted by remaining node lifetime
        (designs/consolidation.md:21-33): 1.0 at creation -> 0.0 at expiry."""
        cost = 0.0
        for p in pods:
            cost += 1.0
            dc = p.metadata.annotations.get(POD_DELETION_COST_ANNOTATION)
            if dc is not None:
                try:
                    cost += float(dc) * 1e-6
                except ValueError:
                    pass
        if claim.expire_after:
            age = now - claim.metadata.creation_timestamp
            remaining = max(0.0, 1.0 - age / claim.expire_after)
            cost *= remaining
        return cost

    # ------------------------------------------------------------------
    # method computation
    # ------------------------------------------------------------------
    def _compute(self, reason: str,
                 candidates: List[Candidate]) -> Optional[Command]:
        if reason == REASON_DRIFTED:
            return self._drift(candidates)
        if reason == REASON_EMPTY:
            return self._emptiness(candidates)
        if reason == REASON_UNDERUTILIZED:
            return (self._multi_consolidation(candidates)
                    or self._single_consolidation(candidates))
        return None

    # -- drift ----------------------------------------------------------
    def _drifted_reason(self, cand: Candidate) -> str:
        try:
            drifted = self.cloudprovider.is_drifted(cand.claim)
        except NodeClaimNotFoundError:
            # the cloud instance vanished behind the cluster's back —
            # not a drift candidate; nodeclaim GC will reap it (core
            # disruption skips candidates whose CloudProvider read errors)
            return ""
        if drifted:
            return "CloudProviderDrifted"
        ann = cand.claim.metadata.annotations
        if ann.get(L.NODEPOOL_HASH_VERSION_ANNOTATION) \
                == L.NODEPOOL_HASH_VERSION and \
                ann.get(L.NODEPOOL_HASH_ANNOTATION,
                        cand.nodepool.hash()) != cand.nodepool.hash():
            return "NodePoolDrifted"
        return ""

    def _drift(self, candidates: List[Candidate]) -> Optional[Command]:
        for cand in candidates:
            if cand.blocked_by:
                continue
            if not self._drifted_reason(cand):
                continue
            if not self._budget_allows([cand], REASON_DRIFTED):
                continue
            # replacement-first: any price, any number of replacements
            result = self._simulate([cand], price_cap=None)
            if result is None:
                continue
            return Command(REASON_DRIFTED, [cand], result.new_nodes)
        return None

    # -- emptiness ------------------------------------------------------
    def _consolidatable_since(self, cand: Candidate) -> float:
        """When the node last changed pod-wise (consolidate_after anchor)."""
        epoch = self._pod_epoch.get(cand.name)
        if epoch is not None:  # always set by _build_candidates (the
            return epoch[1]    # restart path seeds it from claim status)
        cond = cand.claim.conditions.get("Initialized")
        return cond.last_transition if cond else 0.0

    def _past_consolidate_after(self, cand: Candidate) -> bool:
        wait = cand.nodepool.disruption.consolidate_after
        return self.clock() - self._consolidatable_since(cand) >= wait

    def _emptiness(self, candidates: List[Candidate]) -> Optional[Command]:
        empties = [c for c in candidates
                   if not c.pods and not c.blocked_by
                   and c.nodepool.disruption.consolidation_policy in
                   ("WhenEmpty", "WhenEmptyOrUnderutilized")
                   and self._past_consolidate_after(c)]
        picked: List[Candidate] = []
        for cand in empties:
            if self._budget_allows(picked + [cand], REASON_EMPTY):
                picked.append(cand)
        if not picked:
            return None
        return Command(REASON_EMPTY, picked)

    # -- consolidation --------------------------------------------------
    def _consolidatable(self, candidates: List[Candidate]) -> List[Candidate]:
        now = self.clock()
        out = []
        for c in candidates:
            if c.blocked_by or not c.pods:
                continue
            if c.nodepool.disruption.consolidation_policy != "WhenEmptyOrUnderutilized":
                continue
            if not self._past_consolidate_after(c):
                continue
            cond = c.claim.conditions.get("Initialized")
            if cond and now - cond.last_transition < self.consolidation_min_lifetime:
                continue
            out.append(c)
        return out

    def _single_consolidation(
            self, candidates: List[Candidate]) -> Optional[Command]:
        cands = [c for c in self._consolidatable(candidates)
                 if self._budget_allows([c], REASON_UNDERUTILIZED)]
        if not cands:
            return None
        # device-native whole-fleet search: ONE stacked dispatch answers
        # every deletion check (price_cap=0 lanes admit no replacement
        # type, so feasible ⟺ the survivors absorb everything) and every
        # single-node replacement query exactly. The verdict gates are
        # exact, so the first-accept walk below visits the same
        # candidates in the same order as the sequential oracle
        verdicts = self.evaluator.subset_solve(
            self._round_base,
            [self._query([c], 0) for c in cands]
            + [self._query([c], c.price) for c in cands])
        if verdicts is not None:
            for cand, v in zip(cands, verdicts[:len(cands)]):
                if v.feasible and v.n_new == 0:
                    return Command(REASON_UNDERUTILIZED, [cand])
            for cand, v in zip(cands, verdicts[len(cands):]):
                if not (v.feasible and v.n_new == 1):
                    continue
                cmd = self._check_single(cand)
                if cmd is not None:
                    return cmd
            return None
        # batched pre-screen: deletion feasibility for every candidate at once
        delete_ok = self.evaluator.deletions_feasible(
            [self._snapshot([c], price_cap=0) for c in cands])
        for cand, ok in zip(cands, delete_ok):
            if ok:
                return Command(REASON_UNDERUTILIZED, [cand])
        # batched pre-screen of the replacement search: one device call
        # proves most candidates un-replaceable; the (first) survivors get
        # the authoritative simulate, so decisions stay oracle-identical
        maybe = self.evaluator.replacements_prescreen(
            self._round_base, [self._query([c], c.price) for c in cands])
        for cand, m in zip(cands, maybe):
            if not m:
                continue
            cmd = self._check_single(cand)
            if cmd is not None:
                return cmd
        return None

    def _check_single(self, cand: Candidate) -> Optional[Command]:
        """The authoritative single-candidate replacement check, shared
        by the sequential walk and the device-search replay: simulate at
        the candidate's price cap, require exactly one new node plus the
        spot-flexibility floor, and mint the Command from the simulate's
        launch specs — device-path decisions are bit-identical to the
        oracle's by construction, not by re-derivation."""
        result = self._simulate([cand], price_cap=cand.price)
        if result is None or len(result.new_nodes) != 1:
            return None
        if not self._spot_flexibility_ok([cand], result.new_nodes[0]):
            return None
        return Command(REASON_UNDERUTILIZED, [cand], result.new_nodes)

    def _query(self, cands: List[Candidate],
               price_cap: int) -> ReplacementQuery:
        pods = [p for c in cands for p in c.pods]
        # same volume-topology discipline as _snapshot: zonal PV pins are
        # scheduling constraints the pre-screen must see
        self.provisioner._resolve_volume_topology(pods)
        return ReplacementQuery(
            pods=pods,
            gone={c.node.name for c in cands} | {c.name for c in cands},
            price_cap=price_cap)

    def _multi_consolidation(
            self, candidates: List[Candidate]) -> Optional[Command]:
        cands = self._consolidatable(candidates)
        # largest prefix the budgets allow
        while cands and not self._budget_allows(cands, REASON_UNDERUTILIZED):
            cands = cands[:-1]
        if len(cands) < 2:
            return None

        # ONE batched pre-screen covers every prefix the binary search can
        # visit; a False is proof _try_prefix's simulate would fail, so
        # the search only pays for simulates on surviving prefixes.
        # Queries are built incrementally — volume topology resolves once
        # per candidate, not once per (candidate, prefix) pair
        prefix_queries: List[ReplacementQuery] = []
        pods_acc: List[Pod] = []
        gone_acc: Set[str] = set()
        price_acc = 0
        for k, c in enumerate(cands, start=1):
            self.provisioner._resolve_volume_topology(c.pods)
            pods_acc = pods_acc + c.pods
            gone_acc = gone_acc | {c.node.name, c.name}
            price_acc += c.price
            if k >= 2:
                prefix_queries.append(ReplacementQuery(
                    pods=pods_acc, gone=gone_acc, price_cap=price_acc))
        # device-native whole-fleet search: every prefix the binary
        # search can visit re-solves in ONE stacked dispatch, and the
        # verdict gate (feasible with ≤1 new node) is EXACT — it matches
        # _try_prefix's simulate outcome, so the binary-search trajectory
        # is identical to the oracle's and only surviving prefixes pay
        # for the authoritative simulate (which still applies the
        # all-spot-prefix rule and mints the launch specs)
        verdicts = self.evaluator.subset_solve(
            self._round_base, prefix_queries)
        if verdicts is not None:
            maybe = [v.feasible and v.n_new <= 1 for v in verdicts]
        else:
            maybe = self.evaluator.replacements_prescreen(
                self._round_base, prefix_queries)

        # binary-search the largest workable ascending-cost prefix
        # (core firstNConsolidationOption)
        best: Optional[Command] = None
        lo, hi = 2, len(cands)
        while lo <= hi:
            mid = (lo + hi) // 2
            cmd = self._try_prefix(cands[:mid]) if maybe[mid - 2] else None
            if cmd is not None:
                best, lo = cmd, mid + 1
            else:
                hi = mid - 1
        return best

    def _try_prefix(self, cands: List[Candidate]) -> Optional[Command]:
        total_price = sum(c.price for c in cands)
        result = self._simulate(cands, price_cap=total_price)
        if result is None or len(result.new_nodes) > 1:
            return None
        if result.new_nodes and all(
                c.capacity_type == L.CAPACITY_TYPE_SPOT for c in cands):
            # spot→spot replacement is single-node-only (the flexibility
            # floor can't be meaningfully enforced across a merged prefix)
            ct = result.new_nodes[0].requirements.get(L.CAPACITY_TYPE)
            if ct is None or ct.has(L.CAPACITY_TYPE_SPOT):
                return None
        return Command(REASON_UNDERUTILIZED, list(cands), result.new_nodes)

    def _spot_flexibility_ok(self, cands: List[Candidate],
                             plan: NewNodeClaim) -> bool:
        """Spot→spot single-node replacement needs >=15 cheaper types."""
        if not all(c.capacity_type == L.CAPACITY_TYPE_SPOT for c in cands):
            return True
        ct = plan.requirements.get(L.CAPACITY_TYPE)
        if ct is not None and not ct.has(L.CAPACITY_TYPE_SPOT):
            return True  # replacing spot with on-demand: no floor
        return len(plan.instance_type_names) >= MIN_SPOT_FLEXIBILITY

    # ------------------------------------------------------------------
    # simulation
    # ------------------------------------------------------------------
    def _snapshot(self, cands: List[Candidate],
                  price_cap: Optional[int]) -> SchedulingSnapshot:
        """The would-be cluster: candidates gone, their pods pending.

        price_cap semantics: None => full catalog (drift); 0 => no new nodes
        allowed (deletion check); >0 => only types strictly cheaper (the
        filterByPrice discipline that makes any replacement a saving)."""
        gone = {c.node.name for c in cands} | {c.name for c in cands}
        base = self._round_base
        existing = [n for n in base.existing_nodes if n.name not in gone]
        pods = [p for c in cands for p in c.pods]
        # the simulation must honor volume topology exactly like real
        # provisioning would: a pod pinned to a zonal PV (bound since it
        # last scheduled) cannot be consolidated into another zone, and
        # its EBS attachment slots count against the replacement
        self.provisioner._resolve_volume_topology(pods)
        pools = base.nodepools
        if price_cap is not None:
            pools = []
            if price_cap > 0:
                for spec in base.nodepools:
                    kept = InstanceTypes()
                    for it in spec.instance_types:
                        p = it.cheapest_price()
                        if p is not None and p < price_cap:
                            kept.append(it)
                    if kept:
                        pools.append(NodePoolSpec(
                            nodepool=spec.nodepool, instance_types=kept,
                            in_use=spec.in_use))
        return SchedulingSnapshot(
            pods=pods, nodepools=pools, existing_nodes=existing,
            daemon_overheads=base.daemon_overheads, zones=base.zones)

    def _simulate(self, cands: List[Candidate],
                  price_cap: Optional[int]) -> Optional[SolveResult]:
        result = self.solver.solve(self._snapshot(cands, price_cap))
        if result.unschedulable:
            return None
        return result

    # ------------------------------------------------------------------
    # budgets
    # ------------------------------------------------------------------
    def _budget_allows(self, cands: List[Candidate], reason: str) -> bool:
        by_pool: Dict[str, int] = {}
        for c in cands:
            by_pool[c.nodepool.name] = by_pool.get(c.nodepool.name, 0) + 1
        for pool_name, want in by_pool.items():
            pool = next(c.nodepool for c in cands
                        if c.nodepool.name == pool_name)
            total, disrupting = self._pool_counts(pool_name)
            allowed = total  # no budgets => everything allowed
            now = self.clock()
            for budget in pool.disruption.budgets:
                if not budget.allows(reason):
                    continue
                if not budget.active(now):
                    continue  # outside its schedule+duration window
                allowed = min(allowed, budget.max_disruptions(total))
            if disrupting + want > allowed:
                return False
        return True

    def _pool_counts(self, pool_name: str) -> Tuple[int, int]:
        total = disrupting = 0
        for claim in self.kube.list("NodeClaim"):
            if claim.nodepool != pool_name:
                continue
            if not claim.registered:
                continue
            total += 1
            node = self.kube.try_get("Node", claim.node_name) \
                if claim.node_name else None
            if claim.metadata.deletion_timestamp is not None or (
                    node is not None and
                    any(t.key == DISRUPTED_TAINT for t in node.taints)):
                disrupting += 1
        return total, disrupting

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def _execute(self, cmd: Command) -> None:
        if self.metrics is not None:
            self.metrics.inc(
                "karpenter_voluntary_disruption_decisions_total",
                labels={"decision": "replace" if cmd.replacements else "delete",
                        "reason": cmd.reason})
        for cand in cmd.candidates:
            cand.node.taints.append(Taint(DISRUPTED_TAINT, "NoSchedule"))
            self.kube.update(cand.node)
        replacement_claims = []
        pods_by_name = {p.full_name(): p
                        for c in cmd.candidates for p in c.pods}
        for plan in cmd.replacements:
            claim = self.provisioner._create_nodeclaim(plan, pods_by_name)
            replacement_claims.append(claim.name)
        if not replacement_claims:
            self._terminate(cmd)
            return
        self._in_flight.append(_InFlight(
            command=cmd,
            candidate_claims=[c.name for c in cmd.candidates],
            replacement_claims=replacement_claims,
            started=self.clock()))

    def _progress_in_flight(self) -> bool:
        acted = False
        still: List[_InFlight] = []
        for inf in self._in_flight:
            states = []
            for name in inf.replacement_claims:
                claim = self.kube.try_get("NodeClaim", name)
                states.append(claim is not None and claim.initialized)
                if claim is None:
                    states[-1] = None  # replacement failed (ICE etc.)
            if any(s is None for s in states):
                # roll back: untaint candidates, reap surviving
                # replacements, abandon the command
                for name in inf.candidate_claims:
                    claim = self.kube.try_get("NodeClaim", name)
                    if claim and claim.node_name:
                        node = self.kube.try_get("Node", claim.node_name)
                        if node:
                            node.taints = [t for t in node.taints
                                           if t.key != DISRUPTED_TAINT]
                            self.kube.update(node)
                for name in inf.replacement_claims:
                    if self.kube.try_get("NodeClaim", name) is not None:
                        self.kube.delete("NodeClaim", name)
                log.info("disruption rolled back: %s", inf.command.summary())
                if self.metrics is not None:
                    # a command that could not complete = a failed item on
                    # the disruption queue (metrics.md queue_failures)
                    self.metrics.inc(
                        "karpenter_voluntary_disruption_queue"
                        "_failures_total")
                acted = True
                continue
            if all(states):
                self._terminate(inf.command)
                acted = True
            else:
                still.append(inf)
        self._in_flight = still
        return acted

    def _terminate(self, cmd: Command) -> None:
        for cand in cmd.candidates:
            if self.kube.try_get("NodeClaim", cand.name) is not None:
                self.kube.delete("NodeClaim", cand.name)

    # ------------------------------------------------------------------
    # expiration (forceful, v1 semantics)
    # ------------------------------------------------------------------
    def _expire(self) -> int:
        n = 0
        now = self.clock()
        for claim in self.kube.list("NodeClaim"):
            if claim.metadata.deletion_timestamp is not None:
                continue
            if not claim.expire_after:
                continue
            if now - claim.metadata.creation_timestamp >= claim.expire_after:
                self.kube.delete("NodeClaim", claim.name)
                if self.metrics is not None:
                    self.metrics.inc(
                        "karpenter_nodeclaims_disrupted_total",
                        labels={"reason": REASON_EXPIRED})
                n += 1
        return n
