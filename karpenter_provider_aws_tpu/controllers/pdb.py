"""PodDisruptionBudget evaluation shared by the disruption controller
(candidate filtering: a node whose pod is covered by an exhausted PDB is
not a voluntary-disruption candidate) and the terminator (drain rounds
evict at most the remaining allowance per PDB; the claim's
terminationGracePeriod bypasses blocked PDBs the same way it bypasses
do-not-disrupt, karpenter.sh_nodepools.yaml:411)."""

from __future__ import annotations

from typing import List, Tuple


def pdb_state(kube) -> List[Tuple[object, int]]:
    """[(pdb, disruptions currently allowed)] — healthy = bound Running
    matching pods, the policy/v1 controller's healthy count."""
    pods = kube.list("Pod")
    out = []
    for pdb in kube.list("PodDisruptionBudget"):
        matching = [p for p in pods if pdb.matches(p)]
        healthy = sum(1 for p in matching
                      if p.node_name and p.phase == "Running")
        out.append((pdb, pdb.disruptions_allowed(matching, healthy)))
    return out


def blocking_pdb(state: List[Tuple[object, int]], pod):
    """The first exhausted PDB covering ``pod`` (None if evictable)."""
    for pdb, allowed in state:
        if allowed <= 0 and pdb.matches(pod):
            return pdb
    return None


def take_allowance(state: List[Tuple[object, int]], pod) -> bool:
    """Consume one eviction from every PDB covering ``pod``; False (and
    consume nothing) if any covering PDB is exhausted."""
    covering = [i for i, (pdb, _a) in enumerate(state) if pdb.matches(pod)]
    if any(state[i][1] <= 0 for i in covering):
        return False
    for i in covering:
        state[i] = (state[i][0], state[i][1] - 1)
    return True
