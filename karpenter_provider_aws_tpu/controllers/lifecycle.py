"""NodeClaim lifecycle + termination controllers.

Lifecycle mirrors the core nodeclaim lifecycle controller driven through
``CloudProvider.Create`` (SURVEY §3.2): launch (ICE -> delete claim so the
next solve round retries elsewhere), register (Node with matching provider
id joined), initialize (node Ready + capacity known -> discovered-capacity
feedback, capacity/controller.go:54-73). Termination mirrors the core
terminator: cloud instance deleted, then the finalizer clears.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from ..apis import labels as L
from ..apis.objects import (CRITICAL_PRIORITY_CLASSES,  # noqa: F401 re-export
                            NodeClaim, is_critical)
from ..cloudprovider.provider import CloudProvider, parse_instance_id
from ..cloudprovider.types import (CloudProviderError,
                                   InsufficientCapacityError,
                                   NodeClaimNotFoundError)
from ..fake.kube import FakeKube, NotFound
from ..providers.instancetype import InstanceTypeProvider

log = logging.getLogger(__name__)

REGISTRATION_TTL = 15 * 60  # core: claims that never register are reaped

#: eventual-consistency window after CreateFleet: an instance that
#: DescribeInstances has never heard of within this window is "not yet
#: converged", not gone — NotFound shortly after create is retryable
#: (instance.go NotFound handling; the reference GC's NotFound grace).
CREATION_GRACE_SECONDS = 90.0

#: every state DescribeInstances knows — the raw-visibility probe must see
#: terminated instances too (the default filter hides them)
ALL_INSTANCE_STATES = ("pending", "running", "shutting-down", "stopped",
                       "terminated")


def creation_age(claim, now: float) -> float:
    """Seconds since the claim's instance launched (Launched transition,
    falling back to claim creation when the condition is missing)."""
    cond = claim.conditions.get("Launched")
    t0 = cond.last_transition if cond is not None else 0.0
    if not t0:
        t0 = claim.metadata.creation_timestamp
    return now - t0


def instance_visibility(cloudprovider, provider_id: str) -> str:
    """What DescribeInstances across ALL states says about an instance:
    ``live``, ``terminated``, or ``unknown`` (not visible at all).

    The three-way split is what makes the grace window safe: a VISIBLY
    terminated instance is dead and acted on immediately (external
    terminate, spot reclaim), while an instance the API does not return
    in ANY state may simply not have converged into DescribeInstances
    yet — only that case earns the creation-grace benefit of the doubt."""
    iid = parse_instance_id(provider_id)
    insts = cloudprovider.instances.ec2.describe_instances(
        ids=[iid], states=ALL_INSTANCE_STATES)
    if not insts:
        return "unknown"
    if insts[0].state in ("terminated", "shutting-down"):
        return "terminated"
    return "live"


def _release_pod(kube: FakeKube, pod) -> None:
    """The one per-pod release: unbind; non-terminal pods go back to
    Pending (terminal pods are released, never resurrected)."""
    pod.node_name = ""
    if pod.phase not in ("Succeeded", "Failed"):
        pod.phase = "Pending"
    kube.update(pod)


def drain_node_pods(kube: FakeKube, node_name: str, metrics=None) -> None:
    """Release a doomed node's pods back to Pending (terminal pods are
    released, never resurrected). Shared by the terminator and the
    nodeclaim GC so drain semantics cannot diverge."""
    evicted = 0
    for pod in kube.list("Pod"):
        if pod.node_name == node_name:
            if pod.phase not in ("Succeeded", "Failed"):
                evicted += 1
            _release_pod(kube, pod)
    if metrics is not None:
        if evicted:
            metrics.inc("karpenter_nodes_eviction_requests_total", evicted,
                        labels={"node_name": node_name})
        metrics.inc("karpenter_nodes_drained_total")


class NodeClaimLifecycle:
    def __init__(self, kube: FakeKube, cloudprovider: CloudProvider,
                 instance_types: Optional[InstanceTypeProvider] = None,
                 clock=time.time, recorder=None, metrics=None, state=None):
        self.kube = kube
        self.cloudprovider = cloudprovider
        self.instance_types = instance_types
        self.clock = clock
        self.recorder = recorder
        self.metrics = metrics
        self.state = state

    def _count(self, phase: str, claim) -> None:
        """karpenter_nodeclaims_{launched,registered,initialized}_total
        (the core lifecycle counters, metrics.md nodeclaims group)."""
        if self.metrics is not None:
            self.metrics.inc(
                f"karpenter_nodeclaims_{phase}_total",
                labels={"nodepool": claim.nodepool or ""})

    def _event_launch_failed(self, claim, message: str) -> None:
        if self.recorder is not None:
            from ..utils.events import launch_failed
            launch_failed(self.recorder, claim.name, message)

    def reconcile(self) -> dict:
        stats = {"launched": 0, "registered": 0, "initialized": 0,
                 "failed": 0, "reaped": 0}
        for claim in self.kube.list("NodeClaim"):
            if claim.metadata.deletion_timestamp is not None:
                continue
            # core guarantees the termination finalizer on every claim it
            # manages — including standalone ones the provisioner never saw
            if "karpenter.sh/termination" not in claim.metadata.finalizers:
                claim.metadata.finalizers.append("karpenter.sh/termination")
            try:
                if not claim.launched:
                    self._launch(claim)
                    stats["launched"] += 1
                    self._count("launched", claim)
                elif not claim.registered:
                    if self._register(claim):
                        stats["registered"] += 1
                        self._count("registered", claim)
                    elif self.clock() - claim.metadata.creation_timestamp > REGISTRATION_TTL:
                        self.kube.delete("NodeClaim", claim.name)
                        stats["reaped"] += 1
                elif not claim.initialized:
                    if self._initialize(claim):
                        stats["initialized"] += 1
                        self._count("initialized", claim)
            except InsufficientCapacityError as e:
                self._event_launch_failed(claim, str(e))
                # ICE: delete the claim; the offending offerings are already
                # blacklisted so the next solve avoids them (SURVEY §5)
                log.info("nodeclaim %s ICE: %s", claim.name, e)
                claim.set_condition("Launched", "False", "InsufficientCapacity",
                                    str(e), self.clock())
                self._force_delete_claim(claim)
                stats["failed"] += 1
            except CloudProviderError as e:
                self._event_launch_failed(claim, str(e))
                log.warning("nodeclaim %s launch error: %s", claim.name, e)
                claim.set_condition("Launched", "False", "Error", str(e),
                                    self.clock())
                self.kube.update(claim)
                stats["failed"] += 1
        return stats

    def _launch(self, claim: NodeClaim) -> None:
        launched = self.cloudprovider.create(claim)
        claim.provider_id = launched.provider_id
        claim.image_id = launched.image_id
        claim.capacity = launched.capacity
        claim.allocatable = launched.allocatable
        claim.set_condition("Launched", "True", now=self.clock())
        self.kube.update(claim)

    def _register(self, claim: NodeClaim) -> bool:
        for node in self.kube.list("Node"):
            if node.provider_id == claim.provider_id:
                claim.node_name = node.name
                claim.set_condition("Registered", "True", now=self.clock())
                self.kube.update(claim)
                return True
        return False

    def _initialize(self, claim: NodeClaim) -> bool:
        try:
            node = self.kube.get("Node", claim.node_name)
        except NotFound:
            return False
        if not node.ready:
            return False
        claim.set_condition("Initialized", "True", now=self.clock())
        self.kube.update(claim)
        # discovered-capacity reporting is owned by
        # DiscoveredCapacityController (capacity/controller.go:54-73)
        return True

    def _force_delete_claim(self, claim: NodeClaim) -> None:
        self.kube.delete("NodeClaim", claim.name)
        obj = self.kube.try_get("NodeClaim", claim.name)
        if obj is not None:
            self.kube.remove_finalizer(obj, "karpenter.sh/termination")
        # release the pods nominated toward the dead claim NOW — a stale
        # nomination hides them from pending_pods() for its whole TTL, so
        # a failed launch would otherwise stall reprovisioning for 20s
        if self.state is not None:
            self.state.clear_nominations_to(claim.name)


# drain order of a doomed node's pods (termination_test.go:56-61):
# non-critical non-daemonset → non-critical daemonset → critical
# non-daemonset → critical daemonset; a group must be fully gone before
# the next one is evicted. Criticality is the SAME predicate the
# preemption planner's never-victim gate uses (apis/objects.py
# is_critical); CRITICAL_PRIORITY_CLASSES stays re-exported from this
# module for older imports.
def _drain_group(pod) -> int:
    daemon = pod.owner_kind == "DaemonSet"
    return (2 if is_critical(pod) else 0) + (1 if daemon else 0)


class NodeRepairController:
    """Node auto-repair — the consumer of CloudProvider.RepairPolicies
    (cloudprovider.go:252-293): a node whose condition has matched a
    policy's unhealthy status for longer than that policy's toleration
    duration is force-terminated and replaced by the next solve round.
    Repair is forceful: it bypasses budgets, do-not-disrupt, and PDBs
    (a sick kubelet cannot evict anyway), modeled by zeroing the claim's
    terminationGracePeriod so the terminator force-drains immediately."""

    def __init__(self, kube: FakeKube, cloudprovider: CloudProvider,
                 clock=time.time, metrics=None, recorder=None):
        self.kube = kube
        self.cloudprovider = cloudprovider
        self.clock = clock
        self.metrics = metrics
        self.recorder = recorder

    def reconcile(self) -> int:
        policies = self.cloudprovider.repair_policies()
        claims_by_node = {c.node_name: c
                          for c in self.kube.list("NodeClaim")
                          if c.node_name}
        now = self.clock()
        repaired = 0
        for node in self.kube.list("Node"):
            claim = claims_by_node.get(node.metadata.name)
            if claim is None \
                    or claim.metadata.deletion_timestamp is not None:
                continue
            for pol in policies:
                cond = node.conditions.get(pol.condition_type)
                if cond is None or cond.status != pol.condition_status:
                    continue
                if now - cond.last_transition < pol.toleration_duration:
                    continue
                claim.termination_grace_period = 0.0  # forceful drain
                self.kube.update(claim)
                self.kube.delete("NodeClaim", claim.name)
                if self.metrics is not None:
                    # reason-only labels, the family's documented shape
                    # (docs/metrics.md; disruption.py emits it the same
                    # way)
                    self.metrics.inc(
                        "karpenter_nodeclaims_disrupted_total",
                        labels={"reason": "unhealthy"})
                if self.recorder is not None:
                    self.recorder.publish(
                        "NodeClaim", claim.name, "Unhealthy",
                        f"node {node.metadata.name} condition "
                        f"{pol.condition_type}={pol.condition_status} "
                        f"past its {pol.toleration_duration:.0f}s "
                        "toleration; repairing", "Warning")
                repaired += 1
                break
        return repaired


class Terminator:
    """NodeClaim deletion: ordered drain (one group per reconcile, the
    four-group order above), do-not-disrupt pods block the drain until
    the claim's terminationGracePeriod elapses — at which point
    EVERYTHING is force-evicted, bypassing do-not-disrupt
    (karpenter.sh_nodepools.yaml:407-416) — then instance terminated,
    node deleted, finalizer cleared."""

    def __init__(self, kube: FakeKube, cloudprovider: CloudProvider,
                 clock=time.time, metrics=None):
        self.kube = kube
        self.cloudprovider = cloudprovider
        self.clock = clock
        self.metrics = metrics

    def _drain_step(self, claim, pdbs) -> bool:
        """One drain round for a deleting claim's node. Returns True when
        the node holds no more bound pods (drain complete). ``pdbs`` is
        the reconcile-wide allowance state — shared so one pass cannot
        evict more covered pods than a budget allows ACROSS nodes."""
        bound = []
        for p in self.kube.list("Pod"):
            if p.node_name != claim.node_name:
                continue
            if p.phase in ("Succeeded", "Failed"):
                # terminal pods never gate the drain, but they must not
                # outlive the node either (the GC invariant)
                _release_pod(self.kube, p)
            else:
                bound.append(p)
        if not bound:
            return True
        tgp = claim.termination_grace_period
        now = self.clock()
        deadline = None if tgp is None \
            else claim.metadata.deletion_timestamp + tgp
        if deadline is not None and now >= deadline:
            victims = bound
        else:
            blocked, candidates = [], []
            for p in bound:
                (blocked if p.metadata.annotations.get(
                    L.DO_NOT_DISRUPT_ANNOTATION) == "true"
                 else candidates).append(p)
            # preemptive deletion (karpenter.sh_nodepools.yaml:416): a
            # pod whose eviction is blocked — by do-not-disrupt OR by
            # an exhausted PDB — is force-deleted early enough that its
            # own terminationGracePeriodSeconds still fits before the
            # node's deadline. Deadline-driven, so it BYPASSES the
            # drain-group order — waiting behind earlier groups would
            # eat into the very window the preemption exists to protect
            from .pdb import blocking_pdb, take_allowance
            victims = []
            if deadline is not None:
                victims += [
                    p for p in blocked
                    if now >= deadline - p.termination_grace_period_seconds]
                victims += [
                    p for p in candidates
                    if blocking_pdb(pdbs, p) is not None
                    and now >= deadline - p.termination_grace_period_seconds]
            # drain order is decided over ALL non-do-not-disrupt bound
            # pods, INCLUDING ones an exhausted PDB currently blocks
            # (termination_test.go:56-61): a PDB-blocked group-0 pod
            # holds later groups back — critical pods keep running —
            # until its budget frees up or the TGP deadline forces it.
            # Only the current group's PDB-allowed members are evicted
            # this round (karpenter.sh_nodepools.yaml:411).
            if candidates:
                victim_ids = {id(p) for p in victims}
                first = min(_drain_group(p) for p in candidates)
                for p in candidates:
                    if _drain_group(p) == first \
                            and id(p) not in victim_ids \
                            and take_allowance(pdbs, p):
                        victims.append(p)
        for p in victims:
            _release_pod(self.kube, p)
        if self.metrics is not None and victims:
            self.metrics.inc("karpenter_nodes_eviction_requests_total",
                             len(victims),
                             labels={"node_name": claim.node_name})
        return len(victims) == len(bound)

    def _instance_gone(self, claim) -> bool:
        """True when the backing instance no longer exists (or is
        terminating) — spot reclaim, console terminate. Drain is moot on
        a dead machine; upstream cleans such claims up via the
        instance-not-found path rather than waiting on eviction."""
        if not claim.provider_id:
            return False
        try:
            self.cloudprovider.get(claim.provider_id)
            return False
        except NodeClaimNotFoundError:
            pass
        # NotFound: distinguish dead from not-yet-visible. An instance
        # invisible in ANY state within the creation-grace window may
        # still be converging into DescribeInstances — treating it as
        # gone would skip the ordered drain on a machine that is alive.
        vis = instance_visibility(self.cloudprovider, claim.provider_id)
        if vis == "live":
            return False
        if vis == "unknown" \
                and creation_age(claim, self.clock()) < CREATION_GRACE_SECONDS:
            if self.metrics is not None:
                self.metrics.inc(
                    "karpenter_cloud_eventual_consistency_grace_total",
                    labels={"controller": "termination"})
            return False
        return True

    def reconcile(self) -> int:
        from .pdb import pdb_state
        done = 0
        pdbs = None  # computed once, on the first deleting claim
        for claim in self.kube.list("NodeClaim"):
            if claim.metadata.deletion_timestamp is None:
                continue
            if pdbs is None:
                pdbs = pdb_state(self.kube)
            # 1) drain: ordered, do-not-disrupt-aware, TGP-forced. The
            #    instance probe runs only when the drain did not finish
            #    this round — a dead machine (spot reclaim, console
            #    terminate) makes the remaining drain moot
            if claim.node_name and not self._drain_step(claim, pdbs):
                if self._instance_gone(claim):
                    # pods on a dead machine are released, not evicted
                    # (the completion path below counts the drain)
                    drain_node_pods(self.kube, claim.node_name,
                                    metrics=None)
                else:
                    continue  # more drain rounds (or DND wait) needed
            if self.metrics is not None:
                self.metrics.inc(
                    "karpenter_nodeclaims_terminated_total",
                    labels={"nodepool": claim.nodepool or ""})
                self.metrics.observe(
                    "karpenter_nodeclaims_termination_duration_seconds",
                    max(0.0, self.clock()
                        - claim.metadata.deletion_timestamp))
                if claim.node_name:
                    self.metrics.inc("karpenter_nodes_drained_total")
            # 2) terminate the instance
            if claim.provider_id:
                t0 = self.clock()
                try:
                    self.cloudprovider.delete(claim)
                except NodeClaimNotFoundError:
                    pass
                if self.metrics is not None:
                    self.metrics.observe(
                        "karpenter_nodeclaims_instance_termination"
                        "_duration_seconds", max(0.0, self.clock() - t0))
            # 3) delete the Node object
            node = self.kube.try_get("Node", claim.node_name) \
                if claim.node_name else None
            if node is not None:
                if self.metrics is not None:
                    pool = claim.nodepool or ""
                    self.metrics.inc("karpenter_nodes_terminated_total",
                                     labels={"nodepool": pool})
                    self.metrics.observe(
                        "karpenter_nodes_termination_duration_seconds",
                        max(0.0, self.clock()
                            - claim.metadata.deletion_timestamp))
                    self.metrics.observe(
                        "karpenter_nodes_lifetime_duration_seconds",
                        max(0.0, self.clock()
                            - node.metadata.creation_timestamp),
                        labels={"nodepool": pool})
                self.kube.delete("Node", claim.node_name)
            # 4) clear the finalizer -> object goes away
            self.kube.remove_finalizer(claim, "karpenter.sh/termination")
            done += 1
        return done
