"""Control-plane side of the solver sidecar.

``SolverClient`` speaks the raw-bytes gRPC methods; ``RemoteSolver`` is a
drop-in :class:`solver.types.Solver` whose device dispatch rides the wire
(everything else — requirements compilation, canonical ordering, decode —
is identical to the local TPU solver, so decisions are identical by
construction). Topology-constrained snapshots ride the SolveTopo RPC
(the same ops/topo_jax event kernel the local solver runs); snapshots
outside its envelope fall back to the in-process host pour.

Every RPC goes through ONE :class:`resilience.ResiliencePolicy`
(per-call deadlines scaled by payload size, bounded retries with full
jitter, a consecutive-failure circuit breaker). Availability failures
surface as :class:`resilience.SidecarUnavailable` — never a raw
``grpc.RpcError`` — and every ``RemoteSolver`` dispatch path degrades to
the bit-identical host twin, so a flaky or dead sidecar costs latency,
never correctness and never a crash. Peer *rejections* (auth,
validation, capability) do re-raise as grpc errors from ``SolverClient``
— callers that speak the wire directly need the real code — but
``RemoteSolver`` converts those too before they can escape a solve.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..native import arena_pack, arena_unpack
from ..solver.tpu import DeviceDispatchFailed, TPUSolver
from .resilience import ResiliencePolicy, SidecarUnavailable

_SOLVE = "/karpenter.solver.v1.Solver/Solve"
_SOLVE_TOPO = "/karpenter.solver.v1.Solver/SolveTopo"
_SOLVE_PRUNED = "/karpenter.solver.v1.Solver/SolvePruned"
_SOLVE_BATCH = "/karpenter.solver.v1.Solver/SolveBatch"
_SOLVE_SUBSETS = "/karpenter.solver.v1.Solver/SolveSubsets"
_INFO = "/karpenter.solver.v1.Solver/Info"

#: SolveTopo output fields that are booleans on the kernel side (the
#: arena wire carries them as uint8; decode expects real bool masks)
_TOPO_BOOL_OUT = ("types", "zones", "ct", "alive", "bail")


class SolverClient:
    def __init__(self, address: str, timeout: float = 30.0,
                 token: Optional[str] = None,
                 root_cert: Optional[bytes] = None,
                 policy: Optional[ResiliencePolicy] = None,
                 tenant: Optional[str] = None):
        """`token` rides as x-solver-token metadata on every call (the
        server rejects mismatches with UNAUTHENTICATED); `root_cert`
        (PEM) switches the channel to TLS — both optional, matching the
        server's posture flags (sidecar/server.py serve()). `tenant`
        rides as x-solver-tenant metadata — the identity the server's
        admission controller and fair scheduler bill this client's
        solves to (absent = the shared "default" lane). `timeout` is
        the BASE deadline; the policy scales it by payload size per
        call. `policy` defaults to a fresh ResiliencePolicy (retries +
        circuit breaker) shared by all four RPCs of this client."""
        import grpc

        from ..tenancy.admission import TENANT_METADATA_KEY
        self.address = address
        self.timeout = timeout
        self.policy = policy or ResiliencePolicy()
        md = []
        if token:
            md.append(("x-solver-token", token))
        if tenant:
            md.append((TENANT_METADATA_KEY, tenant))
        self._md = tuple(md) or None
        #: per-RPC serialized-request residency (see solve_buffer's
        #: cache_tag): {rpc: (tag, request_bytes)} — ONE entry per RPC,
        #: matching the solver's one resident arena per shape class
        self._req_cache: Dict[str, tuple] = {}
        self.req_cache_stats = {"hits": 0, "misses": 0}
        opts = [("grpc.max_receive_message_length", 256 * 1024 * 1024),
                ("grpc.max_send_message_length", 256 * 1024 * 1024)]
        if root_cert is not None:
            creds = grpc.ssl_channel_credentials(root_certificates=root_cert)
            self._channel = grpc.secure_channel(address, creds, options=opts)
        else:
            self._channel = grpc.insecure_channel(address, options=opts)
        self._solve = self._channel.unary_unary(_SOLVE)
        self._solve_topo = self._channel.unary_unary(_SOLVE_TOPO)
        self._solve_pruned = self._channel.unary_unary(_SOLVE_PRUNED)
        self._solve_batch = self._channel.unary_unary(_SOLVE_BATCH)
        self._solve_subsets = self._channel.unary_unary(_SOLVE_SUBSETS)
        self._info = self._channel.unary_unary(_INFO)

    def _request_bytes(self, rpc: str, cache_tag, statics_key, build):
        """Serialized-request residency: when the caller proves the
        buffer unchanged since its last call (`cache_tag` — the
        RemoteSolver derives it from the resident pack-cache identity +
        patch version), the previous arena_pack output is re-sent as-is
        instead of re-serializing the whole arena every tick. No tag =
        no residency (every one-shot caller keeps the stateless path)."""
        if cache_tag is None:
            return build()
        key = (cache_tag, statics_key)
        ent = self._req_cache.get(rpc)
        if ent is not None and ent[0] == key:
            self.req_cache_stats["hits"] += 1
            return ent[1]
        req = build()
        self._req_cache[rpc] = (key, req)
        self.req_cache_stats["misses"] += 1
        return req

    def solve_buffer(self, buf: np.ndarray, statics: Dict[str, int],
                     cache_tag=None) -> np.ndarray:
        from ..ops.hostpack import STATIC_KEYS

        def build() -> bytes:
            return arena_pack({
                "buf": np.ascontiguousarray(buf, dtype=np.int64),
                "statics": np.array(
                    [statics.get(k, 0) for k in STATIC_KEYS],
                    dtype=np.int64),
            })

        req = self._request_bytes(
            "Solve", cache_tag,
            tuple(statics.get(k, 0) for k in STATIC_KEYS), build)

        def attempt(deadline: float) -> np.ndarray:
            resp = self._solve(req, timeout=deadline, metadata=self._md)
            return np.array(arena_unpack(resp)["out"])  # own the memory

        return self.policy.call(attempt, rpc="Solve",
                                payload_bytes=len(req),
                                base_deadline_s=self.timeout)

    def solve_batch_buffers(self, bufs, statics: Dict[str, int]) -> np.ndarray:
        """B same-shape solves in ONE SolveBatch round trip (the batch
        frame of ops/hostpack.py); returns the [B, out_size] reply rows.
        The whole batch is ONE wire attempt to the resilience policy —
        the breaker counts per RPC, not per batch item."""
        from ..ops.hostpack import pack_batch_frame
        req = arena_pack({"frame": pack_batch_frame(bufs, statics)})
        B = len(bufs)

        def attempt(deadline: float) -> np.ndarray:
            resp = self._solve_batch(req, timeout=deadline,
                                     metadata=self._md)
            out = np.array(arena_unpack(resp)["out"])
            # demux shape check INSIDE the attempt: a reply that lost
            # its batch axis (truncated arena, hostile peer) is a failed
            # attempt, not a crash surfaced to the solve path
            if out.ndim != 2 or out.shape[0] != B:
                raise ValueError(
                    f"SolveBatch reply shape {out.shape} != ({B}, *)")
            return out

        return self.policy.call(attempt, rpc="SolveBatch",
                                payload_bytes=len(req),
                                base_deadline_s=self.timeout)

    def solve_pruned_buffer(self, buf: np.ndarray,
                            statics: Dict[str, int],
                            cache_tag=None) -> np.ndarray:
        """SolvePruned wire: base-solve buffer + (base statics, S); the
        response carries the trailing bail word."""
        from ..ops.hostpack import DEV_PRUNED_SLOTS
        from .server import PRUNED_STATIC_KEYS
        vec = [statics.get(k, 0) for k in PRUNED_STATIC_KEYS]
        if vec[-1] == 0:  # caller predates the S-bearing dispatch site
            vec[-1] = DEV_PRUNED_SLOTS

        def build() -> bytes:
            return arena_pack({
                "buf": np.ascontiguousarray(buf, dtype=np.int64),
                "statics": np.array(vec, dtype=np.int64),
            })

        req = self._request_bytes("SolvePruned", cache_tag, tuple(vec),
                                  build)

        def attempt(deadline: float) -> np.ndarray:
            resp = self._solve_pruned(req, timeout=deadline,
                                      metadata=self._md)
            return np.array(arena_unpack(resp)["out"])

        return self.policy.call(attempt, rpc="SolvePruned",
                                payload_bytes=len(req),
                                base_deadline_s=self.timeout)

    def solve_topo(self, arrays: Dict[str, np.ndarray],
                   rows: Dict[str, np.ndarray],
                   statics: Dict[str, int]) -> Dict[str, np.ndarray]:
        """Topology event-kernel solve over the wire; returns the
        dispatch_topo output dict with bool masks restored."""
        from .server import TOPO_STATIC_KEYS
        req = {"statics": np.array([statics[k] for k in TOPO_STATIC_KEYS],
                                   dtype=np.int64)}
        for k, v in arrays.items():
            req[f"i_{k}"] = np.ascontiguousarray(v)
        for k, v in rows.items():
            req[f"t_{k}"] = np.ascontiguousarray(v)
        packed = arena_pack(req)

        def attempt(deadline: float) -> Dict[str, np.ndarray]:
            resp = self._solve_topo(packed, timeout=deadline,
                                    metadata=self._md)
            # full decode INSIDE the attempt: a truncated response arena
            # (torn write, hostile peer) is a failed attempt, not a
            # crash surfaced to the solve path
            out = {k: np.array(v) for k, v in arena_unpack(resp).items()}
            for k in _TOPO_BOOL_OUT:
                out[k] = out[k].view(bool)
            return out

        return self.policy.call(attempt, rpc="SolveTopo",
                                payload_bytes=len(packed),
                                base_deadline_s=self.timeout)

    def solve_subsets(self, arrays: Dict[str, np.ndarray],
                      lanes: Dict[str, np.ndarray],
                      tprice: np.ndarray,
                      statics: Dict[str, int]) -> np.ndarray:
        """Whole-fleet consolidation subset search over the wire: ONE
        union arena ('i_*') + the per-lane stacks ('q_*') in one round
        trip; returns the [B, 5] SUBSET_OUT_COLS summary rows."""
        from .server import SUBSET_STATIC_KEYS
        req = {"statics": np.array(
            [statics[k] for k in SUBSET_STATIC_KEYS], dtype=np.int64),
            "tprice": np.ascontiguousarray(tprice, dtype=np.int64)}
        for k, v in arrays.items():
            req[f"i_{k}"] = np.ascontiguousarray(v)
        for k, v in lanes.items():
            req[f"q_{k}"] = np.ascontiguousarray(v)
        packed = arena_pack(req)
        B = int(np.asarray(lanes["gid"]).shape[0])

        def attempt(deadline: float) -> np.ndarray:
            resp = self._solve_subsets(packed, timeout=deadline,
                                       metadata=self._md)
            out = np.array(arena_unpack(resp)["out"])
            # demux shape check INSIDE the attempt (same discipline as
            # SolveBatch): a reply that lost its lane axis is a failed
            # attempt, not a crash surfaced to the consolidation round
            if out.ndim != 2 or out.shape[0] != B or out.shape[1] != 5:
                raise ValueError(
                    f"SolveSubsets reply shape {out.shape} != ({B}, 5)")
            return out

        return self.policy.call(attempt, rpc="SolveSubsets",
                                payload_bytes=len(packed),
                                base_deadline_s=self.timeout)

    def info(self, timeout: Optional[float] = None) -> Dict[str, int]:
        def attempt(deadline: float) -> Dict[str, int]:
            out = arena_unpack(self._info(b"", timeout=deadline,
                                          metadata=self._md))
            return {k: int(v[0]) for k, v in out.items()}

        return self.policy.call(attempt, rpc="Info",
                                base_deadline_s=timeout or self.timeout)

    def close(self) -> None:
        self._channel.close()


class RemoteSolver(TPUSolver):
    """TPUSolver whose packed-buffer dispatch is a sidecar round trip.

    backend='auto' (default) cost-routes each solve between the LOCAL
    host twin and the REMOTE device via the same router the in-process
    solver uses — the measured "device" cost now includes the gRPC hop,
    so deployments where the sidecar round trip dominates automatically
    stay local, and ones with a fast fabric ride the device.

    Degradation contract: NO grpc.RpcError escapes any of the four RPC
    paths. Solve maps failures to DeviceDispatchFailed (host twin),
    SolvePruned to the synthetic bail word (host twin), SolveTopo to
    TopoKernelBail (host pour), Info to a not-alive verdict. When the
    client's circuit breaker opens, every router bucket's dev EWMA parks
    at DEV_FAILED_MS and the liveness cache is marked failed, so solves
    route host WITHOUT paying a wire attempt each; the background
    refresh probe doubles as the half-open probe and restores dev
    routing when it succeeds.

    Inherits the incremental encoder's resident packed arena
    (models/delta.py + _run_jax's pack cache): on warm hit/rows ticks
    the buffer shipped over the wire is the PATCHED resident arena —
    no re-encode, no re-pack — while the RPC payload itself stays a
    full arena (the wire protocol is stateless; the server never holds
    client residency)."""

    name = "tpu-sidecar"

    def __init__(self, address: str, n_max: int = 2048,
                 client: Optional[SolverClient] = None,
                 backend: str = "auto", token: Optional[str] = None,
                 root_cert: Optional[bytes] = None,
                 policy: Optional[ResiliencePolicy] = None,
                 tenant: Optional[str] = None):
        """`token`/`root_cert` plumb straight into SolverClient — when the
        server runs with sidecar.token / TLS, the production consumer must
        be able to authenticate (defaults also read from
        SOLVER_SIDECAR_TOKEN so the chart env reaches both containers).
        `tenant` (default SOLVER_SIDECAR_TENANT) names this cluster to a
        shared sidecar pool's admission/fair-scheduling layer."""
        super().__init__(backend=backend, n_max=n_max)
        if client is None:
            import os
            if token is None:
                token = os.environ.get("SOLVER_SIDECAR_TOKEN") or None
            if tenant is None:
                tenant = os.environ.get("SOLVER_SIDECAR_TENANT") or None
            client = SolverClient(address, token=token,
                                  root_cert=root_cert, policy=policy,
                                  tenant=tenant)
        self.client = client
        #: SolvePruned is capability-gated: None until the first ping
        #: fetches the server's Info (an old server without the flag —
        #: or a mesh server — never receives the RPC)
        self._pruned_ok: "Optional[bool]" = None
        #: SolveBatch rides the same gate (no devices==1 requirement:
        #: the server serves it on a mesh too — jit(vmap) on the default
        #: device decides identically)
        self._batch_ok: "Optional[bool]" = None
        #: SolveSubsets (whole-fleet consolidation search) rides the
        #: same gate: the evaluator host-falls-back until the flag is up
        self._subsets_ok: "Optional[bool]" = None
        from ..solver.route import AliveCache
        self._router.alive = AliveCache(self._ping)
        pol = getattr(self.client, "policy", None)
        if pol is not None:
            pol.breaker.on_transition.append(self._on_breaker_transition)

    # -- breaker <-> router wiring --------------------------------------
    def _on_breaker_transition(self, old: str, new: str) -> None:
        from .resilience import CLOSED, OPEN
        alive = self._router.alive
        if new == OPEN:
            # route every bucket to the host twin NOW — don't wait for
            # each shape class to pay its own failed wire attempt
            self._router.park_dev()
            if alive is not None:
                alive.mark_failed()
        elif new == CLOSED and old != CLOSED:
            # half-open probe succeeded: the peer is back; the refresh
            # probe re-measures each bucket's dev EWMA from here
            if alive is not None:
                alive.mark_ok()

    def _wire_evidence(self, served_by: str) -> dict:
        """Dispatch-evidence fields for bench engine reports: retry
        count and breaker state of the last wire call, and which engine
        actually served (`sidecar` or `host-twin`)."""
        pol = getattr(self.client, "policy", None)
        last = getattr(pol, "last_call", None) or {}
        self._wire_stats = dict(
            retries=int(last.get("retries", 0)),
            breaker_state=(pol.breaker.state if pol is not None
                           else "closed"),
            served_by=served_by)
        return self._wire_stats

    def _record_dispatch(self, *a, **kw) -> None:
        super()._record_dispatch(*a, **kw)
        self.last_dispatch_stats.update(
            getattr(self, "_wire_stats", None)
            or dict(retries=0, breaker_state="closed",
                    served_by="sidecar"))

    def _degraded(self, rpc: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(
                "karpenter_solver_sidecar_degraded_solves_total",
                labels={"rpc": rpc})
        # the host twin serves this solve; leave the evidence where the
        # bench engine report reads it even though no kernel dispatched
        self.last_dispatch_stats = dict(
            kernel="host-twin", batch=1, fuse=1, scan_steps=0,
            fused_blocks=0, seq_blocks=0, **self._wire_evidence("host-twin"))

    def _ping(self) -> bool:
        """Sidecar liveness = a short-deadline Info round trip (also
        resolves the SolvePruned capability). Any failure — transport,
        breaker-open, or a MALFORMED Info from a truncated/hostile peer
        — is an explicit not-alive verdict, never an exception
        poisoning the AliveCache probe path."""
        import grpc
        try:
            info = self.client.info(timeout=5.0)
        except (SidecarUnavailable, grpc.RpcError, ValueError, KeyError,
                IndexError, TypeError):
            return False
        devices = info.get("devices")
        if not isinstance(devices, int):
            import logging
            logging.getLogger(__name__).warning(
                "sidecar Info response malformed (no 'devices' field); "
                "treating the sidecar as not alive")
            self._pruned_ok = False
            self._batch_ok = False
            self._subsets_ok = False
            return False
        self._pruned_ok = bool(info.get("pruned", 0)) and devices == 1
        self._batch_ok = bool(info.get("batch", 0))
        self._subsets_ok = bool(info.get("subsets", 0))
        return devices >= 1

    @property
    def supports_pruned_kernel(self) -> bool:
        return bool(self._pruned_ok)

    @property
    def supports_batch_kernel(self) -> bool:
        """True once the server's Info advertised the SolveBatch
        capability — solve_batch callers (consolidation's pre-screen,
        the preference relaxer's re-solves) then ride ONE round trip
        per shape bucket instead of B. An old server never sees the
        RPC; its clients keep the single-solve path."""
        return bool(self._batch_ok)

    @property
    def supports_subset_kernel(self) -> bool:
        """True once the server's Info advertised the SolveSubsets
        capability — the consolidation evaluator's whole-fleet search
        then rides ONE round trip per round. An old server never sees
        the RPC; its clients keep the sequential oracle."""
        return bool(self._subsets_ok)

    def _dev_devices(self) -> int:
        """Always the packed wire dispatch: the SERVER owns the
        mesh-vs-single decision for its local devices (server.py solve)."""
        return 1

    def dispatch_subsets(self, arrays, *, tprice, gid, n, dead, keep,
                         removed_price, n_max: int, E: int,
                         P: int) -> Optional[np.ndarray]:
        """Whole-fleet consolidation subset batch over the wire (ONE
        SolveSubsets round trip). Any failure — transport, breaker,
        peer rejection — returns None: the evaluator then answers the
        round from the sequential oracle (bit-identical by contract),
        never a crash. FAILED_PRECONDITION / UNIMPLEMENTED additionally
        drop the capability flag so a rolled-back peer stops paying a
        doomed round trip per reconcile."""
        import grpc
        arena = {k: arrays[k] for k in (
            "A", "avail_zc", "R", "n", "F", "agz", "agc", "admit",
            "daemon", "pool_types", "pool_agz", "pool_agc", "pool_limit",
            "pool_used0", "ex_alloc", "ex_used0", "ex_compat")}
        wire_lanes = {"gid": gid, "n": n, "dead": dead, "keep": keep,
                      "price": removed_price}
        try:
            out = self.client.solve_subsets(
                arena, wire_lanes, tprice,
                dict(n_max=n_max, E=E, P=P))
        except SidecarUnavailable as e:
            import logging
            logging.getLogger(__name__).warning(
                "SolveSubsets RPC failed (%s); consolidation round on "
                "the sequential oracle", e)
            self._degraded("SolveSubsets")
            return None
        except grpc.RpcError as e:
            import logging
            code = e.code() if hasattr(e, "code") else None
            logging.getLogger(__name__).warning(
                "SolveSubsets RPC rejected (%s); consolidation round on "
                "the sequential oracle", code or e)
            if code in (grpc.StatusCode.FAILED_PRECONDITION,
                        grpc.StatusCode.UNIMPLEMENTED):
                self._subsets_ok = False
            self._degraded("SolveSubsets")
            return None
        self._wire_evidence("sidecar")
        self._record_dispatch(kernel="subset",
                              batch=int(np.asarray(gid).shape[0]),
                              Gp=int(np.asarray(gid).shape[1]), Fu=1)
        return out

    def _resident_tag(self, buf: np.ndarray):
        """Request-residency tag for this dispatch, or None. Only the
        resident pack-cache arena earns one: its identity plus the
        incremental encoder's patch version pin exactly when the BYTES
        last shipped are still the bytes to ship — a rows-tier delta
        patches the buffer IN PLACE (same object, new version), so the
        version in the tag is what forces re-serialization then."""
        pc = getattr(self, "_pack_cache", None)
        if pc is not None and buf is pc.get("buf"):
            return (id(buf), pc.get("version"))
        return None

    def _dispatch(self, buf: np.ndarray, **statics) -> np.ndarray:
        """Base Solve over the wire. Availability failures (retries
        exhausted, breaker open) AND peer rejections both map to
        DeviceDispatchFailed: under backend='auto' the router parks the
        bucket and serves host; backend='jax' catches it in _solve_core
        — either way the bit-identical host twin serves, never a crash,
        and no grpc.RpcError escapes this path."""
        import grpc
        try:
            out = self.client.solve_buffer(
                buf, statics, cache_tag=self._resident_tag(buf))
        except SidecarUnavailable as e:
            import logging
            logging.getLogger(__name__).warning(
                "Solve RPC failed (%s); serving from the host twin", e)
            self._degraded("Solve")
            raise DeviceDispatchFailed(str(e)) from e
        except grpc.RpcError as e:
            import logging
            code = e.code() if hasattr(e, "code") else None
            logging.getLogger(__name__).warning(
                "Solve RPC rejected (%s); serving from the host twin",
                code or e)
            self._degraded("Solve")
            raise DeviceDispatchFailed(
                f"sidecar Solve rejected: {code or e}") from e
        self._wire_evidence("sidecar")
        return out

    def _dispatch_many(self, bufs, **statics) -> np.ndarray:
        """B same-shape buffers, ONE SolveBatch round trip — the wire
        twin of the local vmapped multi-solve. Any failure (transport,
        breaker, peer rejection) maps to DeviceDispatchFailed; the
        caller (TPUSolver.solve_batch) then re-solves each item singly,
        so one bad batch degrades per caller, never crashes, and costs
        exactly one breaker attempt."""
        import grpc
        try:
            out = self.client.solve_batch_buffers(bufs, statics)
        except SidecarUnavailable as e:
            import logging
            logging.getLogger(__name__).warning(
                "SolveBatch RPC failed (%s); re-solving the %d items "
                "singly", e, len(bufs))
            self._degraded("SolveBatch")
            raise DeviceDispatchFailed(str(e)) from e
        except grpc.RpcError as e:
            import logging
            code = e.code() if hasattr(e, "code") else None
            logging.getLogger(__name__).warning(
                "SolveBatch RPC rejected (%s); re-solving the %d items "
                "singly", code or e, len(bufs))
            if code in (grpc.StatusCode.FAILED_PRECONDITION,
                        grpc.StatusCode.UNIMPLEMENTED):
                # the peer cannot speak this RPC anymore (rollback):
                # stop paying a doomed round trip per batch
                self._batch_ok = False
            self._degraded("SolveBatch")
            raise DeviceDispatchFailed(
                f"sidecar SolveBatch rejected: {code or e}") from e
        self._wire_evidence("sidecar")
        return out

    def _dispatch_pruned(self, buf: np.ndarray, **statics) -> np.ndarray:
        """High-G solves ride SolvePruned. A peer that rejects or dies
        mid-call returns a synthetic one-word bail buffer — the caller's
        contract reads only the trailing word, so the bit-identical host
        twin serves, never a crash."""
        import grpc
        try:
            out = self.client.solve_pruned_buffer(
                buf, statics, cache_tag=self._resident_tag(buf))
        except SidecarUnavailable as e:
            import logging
            logging.getLogger(__name__).warning(
                "SolvePruned RPC failed (%s); serving from the host twin",
                e)
            self._degraded("SolvePruned")
            return np.ones(1, dtype=np.int64)  # bail word only
        except grpc.RpcError as e:
            import logging
            code = e.code() if hasattr(e, "code") else None
            logging.getLogger(__name__).warning(
                "SolvePruned RPC failed (%s); serving from the host twin",
                code or e)
            if code in (grpc.StatusCode.FAILED_PRECONDITION,
                        grpc.StatusCode.UNIMPLEMENTED):
                # the peer cannot speak this RPC anymore (mesh restart,
                # rollback): stop paying a doomed round trip per solve
                self._pruned_ok = False
            self._degraded("SolvePruned")
            return np.ones(1, dtype=np.int64)  # bail word only
        self._wire_evidence("sidecar")
        return out

    def _topo_lowerable(self, enc, tenc, existing) -> bool:
        """The local envelope plus the SERVER's SolveTopo bounds
        (sidecar/server.py _TOPO_STATICS_MAX): a snapshot the server
        would reject INVALID_ARGUMENT must route to the host pour here,
        not crash a backend='jax' solve or poison the dev EWMA."""
        if not super()._topo_lowerable(enc, tenc, existing):
            return False
        GZp = max(1, 1 << (max(1, tenc.GZ) - 1).bit_length())
        GHp = max(1, 1 << (max(1, tenc.GH) - 1).bit_length())
        return GZp <= 1 << 12 and GHp <= 1 << 12 \
            and self.n_max <= 1 << 14

    def _dispatch_topo(self, arrays, rows, statics, cache=None):
        """Topology solves ride the SolveTopo RPC: this solver's dev
        engine is the gRPC peer end to end — gated by the sidecar ping
        (router.alive), never by the local accelerator plugin, and the
        router's dev EWMA for topo buckets measures the wire round trip
        it will actually pay. A peer that rejects or dies mid-call maps
        to TopoKernelBail — the bit-identical host pour serves, never a
        crash (cache unused: each wire call re-ships the arena)."""
        import grpc

        from ..solver.tpu import TopoKernelBail
        try:
            out = self.client.solve_topo(arrays, rows, statics)
        except SidecarUnavailable as e:
            import logging
            logging.getLogger(__name__).warning(
                "SolveTopo RPC failed (%s); serving from the host pour",
                e)
            self._degraded("SolveTopo")
            raise TopoKernelBail(f"sidecar SolveTopo failed: {e}") from e
        except grpc.RpcError as e:
            import logging
            logging.getLogger(__name__).warning(
                "SolveTopo RPC failed (%s); serving from the host pour",
                e.code() if hasattr(e, "code") else e)
            self._degraded("SolveTopo")
            raise TopoKernelBail(f"sidecar SolveTopo failed: {e}") from e
        self._wire_evidence("sidecar")
        return out
