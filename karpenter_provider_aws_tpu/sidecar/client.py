"""Control-plane side of the solver sidecar.

``SolverClient`` speaks the raw-bytes gRPC methods; ``RemoteSolver`` is a
drop-in :class:`solver.types.Solver` whose device dispatch rides the wire
(everything else — requirements compilation, canonical ordering, decode —
is identical to the local TPU solver, so decisions are identical by
construction). Topology-constrained snapshots ride the SolveTopo RPC
(the same ops/topo_jax event kernel the local solver runs); snapshots
outside its envelope fall back to the in-process host pour.

Every RPC goes through ONE :class:`resilience.ResiliencePolicy`
(per-call deadlines scaled by payload size, bounded retries with full
jitter, a consecutive-failure circuit breaker). Availability failures
surface as :class:`resilience.SidecarUnavailable` — never a raw
``grpc.RpcError`` — and every ``RemoteSolver`` dispatch path degrades to
the bit-identical host twin, so a flaky or dead sidecar costs latency,
never correctness and never a crash. Peer *rejections* (auth,
validation, capability) do re-raise as grpc errors from ``SolverClient``
— callers that speak the wire directly need the real code — but
``RemoteSolver`` converts those too before they can escape a solve.
"""

from __future__ import annotations

import itertools
import os
import threading
from typing import Dict, Optional

import numpy as np

from ..native import arena_pack, arena_unpack
from ..solver.tpu import DeviceDispatchFailed, TPUSolver
from .resilience import ResiliencePolicy, SidecarUnavailable

_SOLVE = "/karpenter.solver.v1.Solver/Solve"
_SOLVE_TOPO = "/karpenter.solver.v1.Solver/SolveTopo"
_SOLVE_PRUNED = "/karpenter.solver.v1.Solver/SolvePruned"
_SOLVE_BATCH = "/karpenter.solver.v1.Solver/SolveBatch"
_SOLVE_SUBSETS = "/karpenter.solver.v1.Solver/SolveSubsets"
_SOLVE_PATCH = "/karpenter.solver.v1.Solver/SolvePatch"
_INFO = "/karpenter.solver.v1.Solver/Info"

#: client arena tokens: each RemoteSolver mints one so the server can
#: tell two clients behind the same tenant label apart (pid-mixed so a
#: restarted control plane never aliases its predecessor's arenas)
_PATCH_TOKEN_SEQ = itertools.count(1)

#: SolveTopo output fields that are booleans on the kernel side (the
#: arena wire carries them as uint8; decode expects real bool masks)
_TOPO_BOOL_OUT = ("types", "zones", "ct", "alive", "bail")


class SolverClient:
    def __init__(self, address: str, timeout: float = 30.0,
                 token: Optional[str] = None,
                 root_cert: Optional[bytes] = None,
                 policy: Optional[ResiliencePolicy] = None,
                 tenant: Optional[str] = None):
        """`token` rides as x-solver-token metadata on every call (the
        server rejects mismatches with UNAUTHENTICATED); `root_cert`
        (PEM) switches the channel to TLS — both optional, matching the
        server's posture flags (sidecar/server.py serve()). `tenant`
        rides as x-solver-tenant metadata — the identity the server's
        admission controller and fair scheduler bill this client's
        solves to (absent = the shared "default" lane). `timeout` is
        the BASE deadline; the policy scales it by payload size per
        call. `policy` defaults to a fresh ResiliencePolicy (retries +
        circuit breaker) shared by all four RPCs of this client."""
        import grpc

        from ..tenancy.admission import TENANT_METADATA_KEY
        self.address = address
        self.timeout = timeout
        self.policy = policy or ResiliencePolicy()
        md = []
        if token:
            md.append(("x-solver-token", token))
        if tenant:
            md.append((TENANT_METADATA_KEY, tenant))
        self._md = tuple(md) or None
        #: per-RPC serialized-request residency (see solve_buffer's
        #: cache_tag): {rpc: (tag, request_bytes)} — ONE entry per RPC,
        #: matching the solver's one resident arena per shape class
        self._req_cache: Dict[str, tuple] = {}
        self.req_cache_stats = {"hits": 0, "misses": 0}
        opts = [("grpc.max_receive_message_length", 256 * 1024 * 1024),
                ("grpc.max_send_message_length", 256 * 1024 * 1024)]
        if root_cert is not None:
            creds = grpc.ssl_channel_credentials(root_certificates=root_cert)
            self._channel = grpc.secure_channel(address, creds, options=opts)
        else:
            self._channel = grpc.insecure_channel(address, options=opts)
        self._solve = self._channel.unary_unary(_SOLVE)
        self._solve_topo = self._channel.unary_unary(_SOLVE_TOPO)
        self._solve_pruned = self._channel.unary_unary(_SOLVE_PRUNED)
        self._solve_batch = self._channel.unary_unary(_SOLVE_BATCH)
        self._solve_subsets = self._channel.unary_unary(_SOLVE_SUBSETS)
        self._solve_patch = self._channel.unary_unary(_SOLVE_PATCH)
        self._info = self._channel.unary_unary(_INFO)

    def _request_bytes(self, rpc: str, cache_tag, statics_key, build):
        """Serialized-request residency: when the caller proves the
        buffer unchanged since its last call (`cache_tag` — the
        RemoteSolver derives it from the resident pack-cache identity +
        patch version), the previous arena_pack output is re-sent as-is
        instead of re-serializing the whole arena every tick. No tag =
        no residency (every one-shot caller keeps the stateless path)."""
        if cache_tag is None:
            return build()
        key = (cache_tag, statics_key)
        ent = self._req_cache.get(rpc)
        if ent is not None and ent[0] == key:
            self.req_cache_stats["hits"] += 1
            return ent[1]
        req = build()
        self._req_cache[rpc] = (key, req)
        self.req_cache_stats["misses"] += 1
        return req

    def solve_buffer(self, buf: np.ndarray, statics: Dict[str, int],
                     cache_tag=None) -> np.ndarray:
        from ..ops.hostpack import STATIC_KEYS

        def build() -> bytes:
            return arena_pack({
                "buf": np.ascontiguousarray(buf, dtype=np.int64),
                "statics": np.array(
                    [statics.get(k, 0) for k in STATIC_KEYS],
                    dtype=np.int64),
            })

        req = self._request_bytes(
            "Solve", cache_tag,
            tuple(statics.get(k, 0) for k in STATIC_KEYS), build)

        def attempt(deadline: float) -> np.ndarray:
            resp = self._solve(req, timeout=deadline, metadata=self._md)
            return np.array(arena_unpack(resp)["out"])  # own the memory

        return self.policy.call(attempt, rpc="Solve",
                                payload_bytes=len(req),
                                base_deadline_s=self.timeout)

    def solve_batch_buffers(self, bufs, statics: Dict[str, int]) -> np.ndarray:
        """B same-shape solves in ONE SolveBatch round trip (the batch
        frame of ops/hostpack.py); returns the [B, out_size] reply rows.
        The whole batch is ONE wire attempt to the resilience policy —
        the breaker counts per RPC, not per batch item."""
        from ..ops.hostpack import pack_batch_frame
        req = arena_pack({"frame": pack_batch_frame(bufs, statics)})
        B = len(bufs)

        def attempt(deadline: float) -> np.ndarray:
            resp = self._solve_batch(req, timeout=deadline,
                                     metadata=self._md)
            out = np.array(arena_unpack(resp)["out"])
            # demux shape check INSIDE the attempt: a reply that lost
            # its batch axis (truncated arena, hostile peer) is a failed
            # attempt, not a crash surfaced to the solve path
            if out.ndim != 2 or out.shape[0] != B:
                raise ValueError(
                    f"SolveBatch reply shape {out.shape} != ({B}, *)")
            return out

        return self.policy.call(attempt, rpc="SolveBatch",
                                payload_bytes=len(req),
                                base_deadline_s=self.timeout)

    def solve_pruned_buffer(self, buf: np.ndarray,
                            statics: Dict[str, int],
                            cache_tag=None) -> np.ndarray:
        """SolvePruned wire: base-solve buffer + (base statics, S); the
        response carries the trailing bail word."""
        from ..ops.hostpack import DEV_PRUNED_SLOTS
        from .server import PRUNED_STATIC_KEYS
        vec = [statics.get(k, 0) for k in PRUNED_STATIC_KEYS]
        if vec[-1] == 0:  # caller predates the S-bearing dispatch site
            vec[-1] = DEV_PRUNED_SLOTS

        def build() -> bytes:
            return arena_pack({
                "buf": np.ascontiguousarray(buf, dtype=np.int64),
                "statics": np.array(vec, dtype=np.int64),
            })

        req = self._request_bytes("SolvePruned", cache_tag, tuple(vec),
                                  build)

        def attempt(deadline: float) -> np.ndarray:
            resp = self._solve_pruned(req, timeout=deadline,
                                      metadata=self._md)
            return np.array(arena_unpack(resp)["out"])

        return self.policy.call(attempt, rpc="SolvePruned",
                                payload_bytes=len(req),
                                base_deadline_s=self.timeout)

    def solve_topo(self, arrays: Dict[str, np.ndarray],
                   rows: Dict[str, np.ndarray],
                   statics: Dict[str, int]) -> Dict[str, np.ndarray]:
        """Topology event-kernel solve over the wire; returns the
        dispatch_topo output dict with bool masks restored."""
        from .server import TOPO_STATIC_KEYS
        req = {"statics": np.array([statics[k] for k in TOPO_STATIC_KEYS],
                                   dtype=np.int64)}
        for k, v in arrays.items():
            req[f"i_{k}"] = np.ascontiguousarray(v)
        for k, v in rows.items():
            req[f"t_{k}"] = np.ascontiguousarray(v)
        packed = arena_pack(req)

        def attempt(deadline: float) -> Dict[str, np.ndarray]:
            resp = self._solve_topo(packed, timeout=deadline,
                                    metadata=self._md)
            # full decode INSIDE the attempt: a truncated response arena
            # (torn write, hostile peer) is a failed attempt, not a
            # crash surfaced to the solve path
            out = {k: np.array(v) for k, v in arena_unpack(resp).items()}
            for k in _TOPO_BOOL_OUT:
                out[k] = out[k].view(bool)
            return out

        return self.policy.call(attempt, rpc="SolveTopo",
                                payload_bytes=len(packed),
                                base_deadline_s=self.timeout)

    def solve_subsets(self, arrays: Dict[str, np.ndarray],
                      lanes: Dict[str, np.ndarray],
                      tprice: np.ndarray,
                      statics: Dict[str, int]) -> np.ndarray:
        """Whole-fleet consolidation subset search over the wire: ONE
        union arena ('i_*') + the per-lane stacks ('q_*') in one round
        trip; returns the [B, 5] SUBSET_OUT_COLS summary rows."""
        from .server import SUBSET_STATIC_KEYS
        req = {"statics": np.array(
            [statics[k] for k in SUBSET_STATIC_KEYS], dtype=np.int64),
            "tprice": np.ascontiguousarray(tprice, dtype=np.int64)}
        for k, v in arrays.items():
            req[f"i_{k}"] = np.ascontiguousarray(v)
        for k, v in lanes.items():
            req[f"q_{k}"] = np.ascontiguousarray(v)
        packed = arena_pack(req)
        B = int(np.asarray(lanes["gid"]).shape[0])

        def attempt(deadline: float) -> np.ndarray:
            resp = self._solve_subsets(packed, timeout=deadline,
                                       metadata=self._md)
            out = np.array(arena_unpack(resp)["out"])
            # demux shape check INSIDE the attempt (same discipline as
            # SolveBatch): a reply that lost its lane axis is a failed
            # attempt, not a crash surfaced to the consolidation round
            if out.ndim != 2 or out.shape[0] != B or out.shape[1] != 5:
                raise ValueError(
                    f"SolveSubsets reply shape {out.shape} != ({B}, 5)")
            return out

        return self.policy.call(attempt, rpc="SolveSubsets",
                                payload_bytes=len(packed),
                                base_deadline_s=self.timeout)

    def solve_patch_buffer(self, frame: np.ndarray) -> Dict:
        """The delta wire: ship a pre-built patch frame (see
        ops/hostpack.py pack_patch_frame) and return {"out", "resident",
        "version", "wire_bytes"}. The caller builds the frame — it owns
        the resident pack-cache the sections slice from — so this method
        stays stateless like every other SolverClient call."""
        req = arena_pack(
            {"frame": np.ascontiguousarray(frame, dtype=np.int64)})

        def attempt(deadline: float) -> Dict:
            resp = self._solve_patch(req, timeout=deadline,
                                     metadata=self._md)
            out = arena_unpack(resp)
            return {"out": np.array(out["out"]),
                    "resident": int(np.asarray(out["resident"])[0]),
                    "version": int(np.asarray(out["version"])[0]),
                    "wire_bytes": len(req)}

        return self.policy.call(attempt, rpc="SolvePatch",
                                payload_bytes=len(req),
                                base_deadline_s=self.timeout)

    def info(self, timeout: Optional[float] = None) -> Dict[str, int]:
        def attempt(deadline: float) -> Dict[str, int]:
            out = arena_unpack(self._info(b"", timeout=deadline,
                                          metadata=self._md))
            return {k: int(v[0]) for k, v in out.items()}

        return self.policy.call(attempt, rpc="Info",
                                base_deadline_s=timeout or self.timeout)

    def close(self) -> None:
        self._channel.close()


class RemoteSolver(TPUSolver):
    """TPUSolver whose packed-buffer dispatch is a sidecar round trip.

    backend='auto' (default) cost-routes each solve between the LOCAL
    host twin and the REMOTE device via the same router the in-process
    solver uses — the measured "device" cost now includes the gRPC hop,
    so deployments where the sidecar round trip dominates automatically
    stay local, and ones with a fast fabric ride the device.

    Degradation contract: NO grpc.RpcError escapes any of the four RPC
    paths. Solve maps failures to DeviceDispatchFailed (host twin),
    SolvePruned to the synthetic bail word (host twin), SolveTopo to
    TopoKernelBail (host pour), Info to a not-alive verdict. When the
    client's circuit breaker opens, every router bucket's dev EWMA parks
    at DEV_FAILED_MS and the liveness cache is marked failed, so solves
    route host WITHOUT paying a wire attempt each; the background
    refresh probe doubles as the half-open probe and restores dev
    routing when it succeeds.

    Inherits the incremental encoder's resident packed arena
    (models/delta.py + _run_jax's pack cache): on warm hit/rows ticks
    the buffer shipped over the wire is the PATCHED resident arena —
    no re-encode, no re-pack — while the RPC payload itself stays a
    full arena (the wire protocol is stateless; the server never holds
    client residency)."""

    name = "tpu-sidecar"

    def __init__(self, address: str, n_max: int = 2048,
                 client: Optional[SolverClient] = None,
                 backend: str = "auto", token: Optional[str] = None,
                 root_cert: Optional[bytes] = None,
                 policy: Optional[ResiliencePolicy] = None,
                 tenant: Optional[str] = None):
        """`token`/`root_cert` plumb straight into SolverClient — when the
        server runs with sidecar.token / TLS, the production consumer must
        be able to authenticate (defaults also read from
        SOLVER_SIDECAR_TOKEN so the chart env reaches both containers).
        `tenant` (default SOLVER_SIDECAR_TENANT) names this cluster to a
        shared sidecar pool's admission/fair-scheduling layer."""
        super().__init__(backend=backend, n_max=n_max)
        if client is None:
            if token is None:
                token = os.environ.get("SOLVER_SIDECAR_TOKEN") or None
            if tenant is None:
                tenant = os.environ.get("SOLVER_SIDECAR_TENANT") or None
            client = SolverClient(address, token=token,
                                  root_cert=root_cert, policy=policy,
                                  tenant=tenant)
        self.client = client
        #: SolvePruned is capability-gated: None until the first ping
        #: fetches the server's Info (an old server without the flag —
        #: or a mesh server — never receives the RPC)
        self._pruned_ok: "Optional[bool]" = None
        #: SolveBatch rides the same gate (no devices==1 requirement:
        #: the server serves it on a mesh too — jit(vmap) on the default
        #: device decides identically)
        self._batch_ok: "Optional[bool]" = None
        #: SolveSubsets (whole-fleet consolidation search) rides the
        #: same gate: the evaluator host-falls-back until the flag is up
        self._subsets_ok: "Optional[bool]" = None
        #: SolvePatch (delta wire) rides the same gate
        self._patch_ok: "Optional[bool]" = None
        #: what the SERVER holds resident for this client, or None:
        #: {"shape", "epoch", "version"} — the patch-frame state machine
        #: compares it against the local pack cache to pick prime /
        #: delta / clean-resend (sections=[]); any doubt clears it and
        #: the next dispatch re-primes
        self._patch_srv: "Optional[dict]" = None
        self._patch_token = (os.getpid() << 20) ^ next(_PATCH_TOKEN_SEQ)
        #: binding generation: bumped by every bind_client(). Capability
        #: flags and the residency prediction are evidence about ONE
        #: peer — _caps_at records which binding earned them, so a
        #: re-route can never silently apply them to the new replica
        self._bind_gen = 0
        self._caps_at: "Optional[tuple]" = None
        #: serializes encoder/pack-cache access between the tick
        #: pipeline's background prepare and any synchronous solve
        self._enc_lock = threading.RLock()
        #: one speculative (snapshot, prepare-future) slot — armed by
        #: speculate(), consumed or discarded by the next solve()/submit
        self._spec = None
        self._spec_pool = None
        from ..solver.route import AliveCache
        self._router.alive = AliveCache(self._ping)
        #: router dev evidence is keyed by the endpoint serving it — a
        #: re-route must never inherit the old peer's latency verdicts
        self._router.endpoint = getattr(self.client, "address", None)
        pol = getattr(self.client, "policy", None)
        if pol is not None:
            pol.breaker.on_transition.append(self._on_breaker_transition)

    # -- endpoint binding ------------------------------------------------
    def _endpoint_id(self) -> tuple:
        """Identity of the CURRENT wire binding. The generation counter
        (not id(client)) disambiguates: a freed client's id() recycles,
        and two replicas can even share an address through a proxy."""
        return (getattr(self, "_bind_gen", 0),
                getattr(self.client, "address", None))

    def _caps_current(self) -> bool:
        return self._caps_at == self._endpoint_id()

    def bind_client(self, client: SolverClient) -> bool:
        """Swap the wire peer (fleet failover/rebalance, or an explicit
        re-route). ALL endpoint-scoped state dies with the old binding —
        capability flags, the server-residency prediction, any armed
        speculation, and the serialized-request residency the OLD
        channel held — then one Info ping resolves the new peer's
        capabilities. Returns that ping's liveness verdict. The old
        client is left open: the caller (fleet membership) owns its
        lifecycle and may bind back to it later."""
        self._bind_gen += 1
        self.client = client
        self._pruned_ok = None
        self._batch_ok = None
        self._subsets_ok = None
        self._patch_ok = None
        self._patch_srv = None
        self._caps_at = None
        self._spec = None
        self._router.endpoint = getattr(client, "address", None)
        pol = getattr(client, "policy", None)
        if pol is not None and self._on_breaker_transition \
                not in pol.breaker.on_transition:
            pol.breaker.on_transition.append(self._on_breaker_transition)
        alive = self._router.alive
        if self._ping():
            if alive is not None:
                alive.mark_ok()
            return True
        if alive is not None:
            alive.mark_failed()
        return False

    # -- breaker <-> router wiring --------------------------------------
    def _on_breaker_transition(self, old: str, new: str) -> None:
        from .resilience import CLOSED, OPEN
        alive = self._router.alive
        if new == OPEN:
            # route every bucket to the host twin NOW — don't wait for
            # each shape class to pay its own failed wire attempt. Under
            # an endpoint binding only THAT peer's evidence parks: the
            # rest of a fleet keeps the verdicts it earned
            if self._router.endpoint is None:
                self._router.park_dev()
            else:
                self._router.park_dev(endpoint=self._router.endpoint)
            if alive is not None:
                alive.mark_failed()
        elif new == CLOSED and old != CLOSED:
            # half-open probe succeeded at the TRANSPORT level — but the
            # peer that came back may be a different build than the one
            # that died (rolling restart, rollback), so the capability
            # flags resolved at the original ping may now be lies that
            # would turn every gated dispatch into an UNIMPLEMENTED
            # round trip. Re-resolve them with a real Info RPC and let
            # ITS verdict drive the liveness cache, instead of blessing
            # the stale flags with a permanent mark_ok.
            if self._ping():
                if alive is not None:
                    alive.mark_ok()
            elif alive is not None:
                alive.mark_failed()

    def _wire_evidence(self, served_by: str) -> dict:
        """Dispatch-evidence fields for bench engine reports: retry
        count and breaker state of the last wire call, and which engine
        actually served (`sidecar` or `host-twin`)."""
        pol = getattr(self.client, "policy", None)
        last = getattr(pol, "last_call", None) or {}
        self._wire_stats = dict(
            retries=int(last.get("retries", 0)),
            breaker_state=(pol.breaker.state if pol is not None
                           else "closed"),
            served_by=served_by)
        return self._wire_stats

    def _record_dispatch(self, *a, **kw) -> None:
        super()._record_dispatch(*a, **kw)
        self.last_dispatch_stats.update(
            getattr(self, "_wire_stats", None)
            or dict(retries=0, breaker_state="closed",
                    served_by="sidecar"))

    def _degraded(self, rpc: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(
                "karpenter_solver_sidecar_degraded_solves_total",
                labels={"rpc": rpc})
        # the host twin serves this solve; leave the evidence where the
        # bench engine report reads it even though no kernel dispatched
        self.last_dispatch_stats = dict(
            kernel="host-twin", batch=1, fuse=1, scan_steps=0,
            fused_blocks=0, seq_blocks=0, **self._wire_evidence("host-twin"))

    def _ping(self) -> bool:
        """Sidecar liveness = a short-deadline Info round trip (also
        resolves the SolvePruned capability). Any failure — transport,
        breaker-open, or a MALFORMED Info from a truncated/hostile peer
        — is an explicit not-alive verdict, never an exception
        poisoning the AliveCache probe path."""
        import grpc
        try:
            info = self.client.info(timeout=5.0)
        except (SidecarUnavailable, grpc.RpcError, ValueError, KeyError,
                IndexError, TypeError):
            return False
        devices = info.get("devices")
        if not isinstance(devices, int):
            import logging
            logging.getLogger(__name__).warning(
                "sidecar Info response malformed (no 'devices' field); "
                "treating the sidecar as not alive")
            self._pruned_ok = False
            self._batch_ok = False
            self._subsets_ok = False
            self._patch_ok = False
            self._patch_srv = None
            self._caps_at = self._endpoint_id()
            return False
        self._pruned_ok = bool(info.get("pruned", 0)) and devices == 1
        self._batch_ok = bool(info.get("batch", 0))
        self._subsets_ok = bool(info.get("subsets", 0))
        self._patch_ok = bool(info.get("patch", 0))
        # the flags are evidence about THIS binding's peer only
        self._caps_at = self._endpoint_id()
        # whatever server answered, our resident arena (if any) lived in
        # the PREVIOUS process — re-prime rather than patch into a void
        self._patch_srv = None
        return devices >= 1

    @property
    def supports_pruned_kernel(self) -> bool:
        return bool(self._pruned_ok) and self._caps_current()

    @property
    def supports_ckpt_kernel(self) -> bool:
        """Always False: the checkpoint bank must live NEXT TO the
        kernel that replays it, and for a remote solver that is the
        sidecar — server.py keeps a per-arena bank and serves the
        suffix re-solve off the SolvePatch wire's own dirty sections,
        so a client-side bank would only duplicate state that can go
        stale across the wire."""
        return False

    @property
    def supports_batch_kernel(self) -> bool:
        """True once the server's Info advertised the SolveBatch
        capability — solve_batch callers (consolidation's pre-screen,
        the preference relaxer's re-solves) then ride ONE round trip
        per shape bucket instead of B. An old server never sees the
        RPC; its clients keep the single-solve path."""
        return bool(self._batch_ok) and self._caps_current()

    @property
    def supports_subset_kernel(self) -> bool:
        """True once the server's Info advertised the SolveSubsets
        capability — the consolidation evaluator's whole-fleet search
        then rides ONE round trip per round. An old server never sees
        the RPC; its clients keep the sequential oracle."""
        return bool(self._subsets_ok) and self._caps_current()

    @property
    def supports_preempt_kernel(self) -> bool:
        """No Preempt RPC: the preemption planner's lane batch is tiny
        (≤64 lanes over shared tables) and its numpy twin is
        bit-identical by contract, so remote callers keep the host
        path rather than pay a wire round trip per search."""
        return False

    def _dev_devices(self) -> int:
        """Always the packed wire dispatch: the SERVER owns the
        mesh-vs-single decision for its local devices (server.py solve)."""
        return 1

    def dispatch_subsets(self, arrays, *, tprice, gid, n, dead, keep,
                         removed_price, n_max: int, E: int,
                         P: int) -> Optional[np.ndarray]:
        """Whole-fleet consolidation subset batch over the wire (ONE
        SolveSubsets round trip). Any failure — transport, breaker,
        peer rejection — returns None: the evaluator then answers the
        round from the sequential oracle (bit-identical by contract),
        never a crash. FAILED_PRECONDITION / UNIMPLEMENTED additionally
        drop the capability flag so a rolled-back peer stops paying a
        doomed round trip per reconcile."""
        import grpc
        arena = {k: arrays[k] for k in (
            "A", "avail_zc", "R", "n", "F", "agz", "agc", "admit",
            "daemon", "pool_types", "pool_agz", "pool_agc", "pool_limit",
            "pool_used0", "ex_alloc", "ex_used0", "ex_compat")}
        wire_lanes = {"gid": gid, "n": n, "dead": dead, "keep": keep,
                      "price": removed_price}
        try:
            out = self.client.solve_subsets(
                arena, wire_lanes, tprice,
                dict(n_max=n_max, E=E, P=P))
        except SidecarUnavailable as e:
            import logging
            logging.getLogger(__name__).warning(
                "SolveSubsets RPC failed (%s); consolidation round on "
                "the sequential oracle", e)
            self._degraded("SolveSubsets")
            return None
        except grpc.RpcError as e:
            import logging
            code = e.code() if hasattr(e, "code") else None
            logging.getLogger(__name__).warning(
                "SolveSubsets RPC rejected (%s); consolidation round on "
                "the sequential oracle", code or e)
            if code in (grpc.StatusCode.FAILED_PRECONDITION,
                        grpc.StatusCode.UNIMPLEMENTED):
                self._subsets_ok = False
            self._degraded("SolveSubsets")
            return None
        self._wire_evidence("sidecar")
        self._record_dispatch(kernel="subset",
                              batch=int(np.asarray(gid).shape[0]),
                              Gp=int(np.asarray(gid).shape[1]), Fu=1)
        return out

    def _resident_tag(self, buf: np.ndarray):
        """Request-residency tag for this dispatch, or None. Only the
        resident pack-cache arena earns one: its identity plus the
        incremental encoder's patch version pin exactly when the BYTES
        last shipped are still the bytes to ship — a rows-tier delta
        patches the buffer IN PLACE (same object, new version), so the
        version in the tag is what forces re-serialization then. The
        arena epoch rides too: a structural rebuild frees the old
        buffer, and id() values recycle — (id, version) alone could
        alias a NEW arena onto a dead tag and re-send stale bytes."""
        pc = getattr(self, "_pack_cache", None)
        if pc is not None and buf is pc.get("buf"):
            return (id(buf), pc.get("version"), tuple(self.arena_epoch()))
        return None

    # -- delta wire (SolvePatch) ----------------------------------------
    def _patch_plan(self, buf: np.ndarray, statics: Dict[str, int]):
        """Decide how this dispatch rides the delta wire, or None (full
        Solve). Compares the local resident pack cache against what the
        server holds for this client and picks, cheapest first:

        - "clean": server is at our version — header-only resend
        - "delta": server is one recorded transition behind — ship the
          dirty (start, stop) sections patch_inputs1 just overwrote
        - "prime": anything else — ship the whole arena once to
          (re)establish residency; warm ticks then ride deltas

        Returns {"frame", "kind", "version", "shape", "epoch",
        "payload_words", "endpoint"}."""
        if not self._patch_ok or not self._caps_current():
            return None
        pc = getattr(self, "_pack_cache", None)
        if pc is None or pc.get("buf") is None or buf is not pc["buf"]:
            return None
        epoch = self.arena_epoch()
        if epoch[0] is None:
            return None
        from ..ops.hostpack import (PATCH_MAX_SECTIONS,
                                    pack_patch_frame_from)
        from .server import PATCH_LAYOUT_KEYS
        shape = tuple(int(statics.get(k, 0)) for k in PATCH_LAYOUT_KEYS)
        ver = int(pc.get("version") or 0)
        srv = self._patch_srv
        kind, base, spans = "prime", -1, None
        if srv is not None and srv["shape"] == shape \
                and srv["epoch"] == epoch:
            if srv["version"] == ver:
                # grow-loop redispatch / re-solve of the same tick: the
                # resident copy is already exactly this buffer
                kind, base, spans = "clean", ver, []
            else:
                sec = pc.get("sections")
                if sec is not None and sec.get("base") == srv["version"] \
                        and sec.get("to") == ver \
                        and len(sec.get("spans") or []) \
                        <= PATCH_MAX_SECTIONS:
                    kind, base = "delta", srv["version"]
                    spans = list(sec.get("spans") or [])
        if spans is None:
            kind, base = "prime", -1
            spans = [(0, int(buf.size))]
        # zero-copy assembly: payload words flow from the resident pack
        # buffer straight into the preallocated frame — no per-section
        # copies, no concatenate chain (ops/hostpack.py)
        frame = pack_patch_frame_from(buf, spans, statics,
                                      token=self._patch_token,
                                      epoch=epoch, base_version=base,
                                      new_version=ver)
        # optimistic residency prediction: the pipelined prepare of tick
        # N+1 runs while tick N's RPC is still in flight, so it must
        # plan against where the server WILL be, not where it was — a
        # wrong prediction (tick N failed) is caught by the server's
        # version check and costs one full Solve, never a stale solve
        self._patch_srv = dict(shape=shape, epoch=epoch, version=ver)
        return dict(frame=frame, kind=kind, version=ver, shape=shape,
                    epoch=epoch, endpoint=self._endpoint_id(),
                    payload_words=sum(s1 - s0 for s0, s1 in spans))

    def _patch_fallback(self, reason: str) -> None:
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_wire_fallback_total",
                             labels={"reason": reason})

    def _dispatch_patch(self, plan: dict) -> Optional[np.ndarray]:
        """One SolvePatch attempt. Returns the output buffer, or None
        when the peer rejected the patch — the caller then serves this
        tick with ONE full Solve (never a second patch). Transport
        failure raises DeviceDispatchFailed like the full-frame path:
        the host twin serves, no extra wire attempt against a peer the
        policy just declared unavailable."""
        import grpc
        if plan.get("endpoint") is not None \
                and plan["endpoint"] != self._endpoint_id():
            # planned against a peer we no longer talk to (failover or
            # rebalance landed between prepare and dispatch): a patch
            # frame must NEVER cross a re-route — the new replica holds
            # nothing resident (and may not even speak the RPC). This
            # tick rides one full Solve and the next plan re-primes.
            self._patch_srv = None
            self._patch_fallback("no_resident")
            return None
        try:
            reply = self.client.solve_patch_buffer(plan["frame"])
        except SidecarUnavailable as e:
            import logging
            logging.getLogger(__name__).warning(
                "SolvePatch RPC failed (%s); serving from the host twin",
                e)
            self._patch_srv = None
            self._patch_fallback("transport")
            self._degraded("SolvePatch")
            raise DeviceDispatchFailed(str(e)) from e
        except grpc.RpcError as e:
            import logging
            code = e.code() if hasattr(e, "code") else None
            try:
                details = (e.details() or "") if hasattr(e, "details") \
                    else ""
            except Exception:
                details = ""
            self._patch_srv = None
            if code == grpc.StatusCode.UNIMPLEMENTED:
                # the peer cannot speak this RPC anymore (rollback):
                # stop paying a doomed round trip per tick
                self._patch_ok = False
                reason = "unimplemented"
            elif code == grpc.StatusCode.FAILED_PRECONDITION:
                reason = "stale_version" if "stale" in details \
                    else "no_resident"
            else:
                reason = "rejected"
            logging.getLogger(__name__).warning(
                "SolvePatch %s rejected (%s: %s); this tick rides one "
                "full Solve", plan["kind"], code or e, reason)
            self._patch_fallback(reason)
            return None
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_wire_patch_total",
                             labels={"kind": plan["kind"]})
            self.metrics.inc("karpenter_solver_wire_patch_bytes",
                             value=float(reply["wire_bytes"]))
        # resident=0: the server solved but would not hold the arena
        # (table full of hot arenas) — keep full-framing, no error
        # noise. On success, never REGRESS the prediction: the pipelined
        # prepare may already have advanced _patch_srv past this tick.
        if not reply["resident"]:
            self._patch_srv = None
        elif self._patch_srv is None:
            self._patch_srv = dict(shape=plan["shape"],
                                   epoch=plan["epoch"],
                                   version=plan["version"])
        self._wire_evidence("sidecar")
        return reply["out"]

    def _dispatch(self, buf: np.ndarray, **statics) -> np.ndarray:
        """Base Solve over the wire — via the delta wire (SolvePatch)
        when the server holds this client's arena resident, the full
        frame otherwise. Availability failures (retries exhausted,
        breaker open) AND peer rejections both map to
        DeviceDispatchFailed: under backend='auto' the router parks the
        bucket and serves host; backend='jax' catches it in _solve_core
        — either way the bit-identical host twin serves, never a crash,
        and no grpc.RpcError escapes this path."""
        import grpc
        plan = self._patch_plan(buf, statics)
        if plan is not None:
            out = self._dispatch_patch(plan)
            if out is not None:
                return out
        try:
            out = self.client.solve_buffer(
                buf, statics, cache_tag=self._resident_tag(buf))
        except SidecarUnavailable as e:
            import logging
            logging.getLogger(__name__).warning(
                "Solve RPC failed (%s); serving from the host twin", e)
            self._degraded("Solve")
            raise DeviceDispatchFailed(str(e)) from e
        except grpc.RpcError as e:
            import logging
            code = e.code() if hasattr(e, "code") else None
            logging.getLogger(__name__).warning(
                "Solve RPC rejected (%s); serving from the host twin",
                code or e)
            self._degraded("Solve")
            raise DeviceDispatchFailed(
                f"sidecar Solve rejected: {code or e}") from e
        self._wire_evidence("sidecar")
        return out

    def _dispatch_many(self, bufs, **statics) -> np.ndarray:
        """B same-shape buffers, ONE SolveBatch round trip — the wire
        twin of the local vmapped multi-solve. Any failure (transport,
        breaker, peer rejection) maps to DeviceDispatchFailed; the
        caller (TPUSolver.solve_batch) then re-solves each item singly,
        so one bad batch degrades per caller, never crashes, and costs
        exactly one breaker attempt."""
        import grpc
        try:
            out = self.client.solve_batch_buffers(bufs, statics)
        except SidecarUnavailable as e:
            import logging
            logging.getLogger(__name__).warning(
                "SolveBatch RPC failed (%s); re-solving the %d items "
                "singly", e, len(bufs))
            self._degraded("SolveBatch")
            raise DeviceDispatchFailed(str(e)) from e
        except grpc.RpcError as e:
            import logging
            code = e.code() if hasattr(e, "code") else None
            logging.getLogger(__name__).warning(
                "SolveBatch RPC rejected (%s); re-solving the %d items "
                "singly", code or e, len(bufs))
            if code in (grpc.StatusCode.FAILED_PRECONDITION,
                        grpc.StatusCode.UNIMPLEMENTED):
                # the peer cannot speak this RPC anymore (rollback):
                # stop paying a doomed round trip per batch
                self._batch_ok = False
            self._degraded("SolveBatch")
            raise DeviceDispatchFailed(
                f"sidecar SolveBatch rejected: {code or e}") from e
        self._wire_evidence("sidecar")
        return out

    def _dispatch_pruned(self, buf: np.ndarray, **statics) -> np.ndarray:
        """High-G solves ride SolvePruned. A peer that rejects or dies
        mid-call returns a synthetic one-word bail buffer — the caller's
        contract reads only the trailing word, so the bit-identical host
        twin serves, never a crash."""
        import grpc
        try:
            out = self.client.solve_pruned_buffer(
                buf, statics, cache_tag=self._resident_tag(buf))
        except SidecarUnavailable as e:
            import logging
            logging.getLogger(__name__).warning(
                "SolvePruned RPC failed (%s); serving from the host twin",
                e)
            self._degraded("SolvePruned")
            return np.ones(1, dtype=np.int64)  # bail word only
        except grpc.RpcError as e:
            import logging
            code = e.code() if hasattr(e, "code") else None
            logging.getLogger(__name__).warning(
                "SolvePruned RPC failed (%s); serving from the host twin",
                code or e)
            if code in (grpc.StatusCode.FAILED_PRECONDITION,
                        grpc.StatusCode.UNIMPLEMENTED):
                # the peer cannot speak this RPC anymore (mesh restart,
                # rollback): stop paying a doomed round trip per solve
                self._pruned_ok = False
            self._degraded("SolvePruned")
            return np.ones(1, dtype=np.int64)  # bail word only
        self._wire_evidence("sidecar")
        return out

    # -- pipelined ticks ------------------------------------------------
    def speculate(self, snapshot) -> None:
        """Start the delta-encode/pack walk for ``snapshot`` on the
        background serializer thread NOW (the batcher window just
        opened) instead of when solve() is called (the window closed).
        solve() consumes the speculation only when handed the SAME
        snapshot object with the encoder untouched in between —
        anything else discards it and re-encodes, so speculation can
        produce a wasted encode, never a stale solve."""
        from concurrent.futures import ThreadPoolExecutor
        if self._spec_pool is None:
            self._spec_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tick-prep")
        self._spec = (snapshot,
                      self._spec_pool.submit(self._prepare_tick, snapshot))

    def solve(self, snapshot):
        spec, self._spec = self._spec, None
        if spec is not None:
            prep = spec[1].result()
            if spec[0] is snapshot and not prep.get("monolithic") \
                    and self._delta is not None \
                    and self._delta.state_token() == prep["etoken"]:
                return self._dispatch_prepared(prep)
            # stale speculation (different snapshot, or the encoder
            # moved underneath it): its planned patch frame never
            # shipped, so the residency prediction points into a
            # version hole — drop it and re-prime on the next dispatch
            # instead of paying a guaranteed stale-version round trip
            if not prep.get("monolithic"):
                self._patch_srv = None
        with self._enc_lock:
            return super().solve(snapshot)

    def _prepare_tick(self, snapshot) -> dict:
        """Stage 1 of the pipelined tick: everything UP TO the wire —
        delta encode, resident-arena patch, request planning — run under
        the encoder lock on the serializer thread. Returns a prepared
        dict whose contents are safe to dispatch while the NEXT tick's
        prepare mutates the encoder: payloads and the arena are copied,
        and the per-group pod lists are captured before a rows-tier
        delta can replace them. Ineligible snapshots (topology, host-
        only, over the device group cap, non-jax backend) return a
        monolithic marker — the dispatch stage then runs the ordinary
        solve under the lock."""
        import time as _time
        with self._enc_lock:
            t0 = _time.perf_counter()
            mono = {"monolithic": True, "snapshot": snapshot}
            if not snapshot.pods or self._delta is None \
                    or self.backend != "jax":
                return mono
            from ..solver.route import dev_engine_usable
            if not dev_engine_usable(self._router):
                return mono
            existing = sorted(snapshot.existing_nodes,
                              key=lambda n: n.name)
            self._delta.metrics = self.metrics
            enc, (ex_alloc, ex_used, ex_compat), delta = \
                self._delta.encode(snapshot, None, existing)
            self._last_delta = delta
            if enc.topo_any or not enc.types \
                    or len(enc.groups) > self._dev_group_cap(enc):
                return mono
            arrays, stt, buf, _ = self._arena_for(
                enc, ex_alloc, ex_used, ex_compat, 1)
            if buf is None:
                return mono
            if stt["G"] > self.dev_max_groups:
                return mono  # pruned territory: the monolithic path owns it
            statics = dict(T=stt["T"], D=stt["D"], Z=stt["Z"],
                           C=stt["C"], G=stt["G"], E=stt["E"],
                           P=stt["P"], K=stt["K"], V=stt["V"],
                           M=stt["M"], n_max=self._bucket, F=stt["F"],
                           Q=stt.get("Q", 0))
            plan = self._patch_plan(buf, statics)
            fuse = arrays.get("fuse")
            prep = dict(
                snapshot=snapshot, enc=enc, existing=existing,
                pods_by_group=[g.pods for g in enc.groups],
                G=len(enc.groups), E=ex_alloc.shape[0],
                D=enc.A.shape[1], stt=dict(stt), statics=statics,
                n_bucket=self._bucket,
                # the dispatch stage's fallback full Solve must ship the
                # bytes of THIS version — the resident buffer itself gets
                # patched in place by the next prepare
                buf_snap=np.array(buf, copy=True), plan=plan,
                fuse=(np.array(fuse, copy=True)
                      if fuse is not None else None),
                tier=delta.tier, patched_rows=delta.patched_rows,
                etoken=self._delta.state_token(),
                encode_ms=(_time.perf_counter() - t0) * 1e3)
            return prep

    def _dispatch_prepared(self, prep: dict):
        """Stage 2 of the pipelined tick: the wire round trip + decode,
        off the encoder lock — free to overlap with the next tick's
        prepare. Every failure path (patch rejected AND full Solve
        failed, slot exhaustion) re-enters the monolithic solve under
        the lock: the incremental encoder re-serves the same snapshot
        from its resident state, so the retry costs a hit-tier encode,
        and decisions stay oracle-identical by the encoder contract."""
        import time as _time
        if prep.get("monolithic"):
            with self._enc_lock:
                return super().solve(prep["snapshot"])
        from ..ops.hostpack import unpack_outputs1
        from ..solver.tpu import _slotmap
        stt, statics = prep["stt"], prep["statics"]
        n_bucket = prep["n_bucket"]
        G, E, D = prep["G"], prep["E"], prep["D"]
        T, Dp, Z, C = stt["T"], stt["D"], stt["Z"], stt["C"]
        Gp, Ep, Pp = stt["G"], stt["E"], stt["P"]
        Fu = stt["F"]
        t_rpc = _time.perf_counter()
        try:
            o_buf = None
            if prep["plan"] is not None:
                o_buf = self._dispatch_patch(prep["plan"])
            if o_buf is None:
                o_buf = self._dispatch(prep["buf_snap"], **statics)
            out = unpack_outputs1(o_buf, T, Dp, Z, C, Gp, Ep, Pp,
                                  n_bucket)
            if out["leftover"].sum() > 0 \
                    and int(out["num_nodes"][0]) >= n_bucket:
                # slot exhaustion: the monolithic path owns the grow
                # loop (and the n_max reset discipline around it)
                raise DeviceDispatchFailed("pipelined tick exhausted "
                                           "new-node slots")
        except DeviceDispatchFailed:
            with self._enc_lock:
                return super().solve(prep["snapshot"])
        t_dec = _time.perf_counter()
        self._record_dispatch(
            kernel=("fused" if Fu > 1 else "base"), batch=1, Gp=Gp,
            Fu=Fu, fuse=prep["fuse"] if Fu > 1 else None)
        takes = out["takes"][:G]
        takes = np.concatenate([takes[:, :E], takes[:, Ep:]], axis=1)
        sm = _slotmap(E, Ep, Ep + n_bucket)
        final = dict(
            types=out["types"][sm], zones=out["zones"][sm],
            ct=out["ct"][sm], pool=out["pool"][sm],
            alive=out["alive"][sm], used=out["used"][sm][:, :D], E=E)
        res = self._decode(prep["enc"], prep["existing"], takes,
                           out["leftover"][:G], final,
                           pods_by_group=prep["pods_by_group"])
        self.last_phase_stats = dict(
            encode_ms=prep["encode_ms"],
            kernel_ms=(t_dec - t_rpc) * 1e3,
            decode_ms=(_time.perf_counter() - t_dec) * 1e3,
            cache=prep["tier"], patched_rows=prep["patched_rows"])
        return res

    def _topo_lowerable(self, enc, tenc, existing) -> bool:
        """The local envelope plus the SERVER's SolveTopo bounds
        (sidecar/server.py _TOPO_STATICS_MAX): a snapshot the server
        would reject INVALID_ARGUMENT must route to the host pour here,
        not crash a backend='jax' solve or poison the dev EWMA."""
        if not super()._topo_lowerable(enc, tenc, existing):
            return False
        GZp = max(1, 1 << (max(1, tenc.GZ) - 1).bit_length())
        GHp = max(1, 1 << (max(1, tenc.GH) - 1).bit_length())
        return GZp <= 1 << 12 and GHp <= 1 << 12 \
            and self.n_max <= 1 << 14

    def _dispatch_topo(self, arrays, rows, statics, cache=None):
        """Topology solves ride the SolveTopo RPC: this solver's dev
        engine is the gRPC peer end to end — gated by the sidecar ping
        (router.alive), never by the local accelerator plugin, and the
        router's dev EWMA for topo buckets measures the wire round trip
        it will actually pay. A peer that rejects or dies mid-call maps
        to TopoKernelBail — the bit-identical host pour serves, never a
        crash (cache unused: each wire call re-ships the arena)."""
        import grpc

        from ..solver.tpu import TopoKernelBail
        try:
            out = self.client.solve_topo(arrays, rows, statics)
        except SidecarUnavailable as e:
            import logging
            logging.getLogger(__name__).warning(
                "SolveTopo RPC failed (%s); serving from the host pour",
                e)
            self._degraded("SolveTopo")
            raise TopoKernelBail(f"sidecar SolveTopo failed: {e}") from e
        except grpc.RpcError as e:
            import logging
            logging.getLogger(__name__).warning(
                "SolveTopo RPC failed (%s); serving from the host pour",
                e.code() if hasattr(e, "code") else e)
            self._degraded("SolveTopo")
            raise TopoKernelBail(f"sidecar SolveTopo failed: {e}") from e
        self._wire_evidence("sidecar")
        return out


class TickPipeline:
    """Double-buffered tick pipeline over a :class:`RemoteSolver`.

    ``submit(snapshot)`` returns a Future; while tick N's RPC is in
    flight on the dispatch thread, tick N+1's delta-encode/pack runs on
    the serializer thread — the encode hides behind the wire round trip
    instead of adding to it. Depth is bounded at two outstanding ticks
    (one in flight + one preparing): a third submit blocks on the oldest
    result, so a slow sidecar backpressures the control plane instead of
    growing an unbounded queue. Breaker/retry/degradation semantics are
    untouched — the ResiliencePolicy wraps each RPC attempt exactly as
    in the synchronous path; the pipeline only changes WHEN the encode
    work happens, never what rides the wire or how failures degrade.

    ``speculate(snapshot)`` arms the solver's speculative prepare (see
    RemoteSolver.speculate); the next submit of the SAME snapshot object
    consumes it."""

    #: outstanding ticks (in-flight RPC + preparing) before submit blocks
    MAX_DEPTH = 2

    def __init__(self, solver: RemoteSolver, metrics=None):
        import collections
        from concurrent.futures import ThreadPoolExecutor
        self.solver = solver
        self.metrics = metrics if metrics is not None else solver.metrics
        self._prep_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tick-prep")
        self._rpc_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tick-rpc")
        self._inflight = collections.deque()

    def speculate(self, snapshot) -> None:
        self.solver.speculate(snapshot)

    def _gauge_depth(self) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge("karpenter_solver_pipeline_depth",
                                   float(len(self._inflight)))

    def submit(self, snapshot):
        """Enqueue one tick; returns a Future[SolveResult]."""
        while len(self._inflight) >= self.MAX_DEPTH:
            self._inflight.popleft().result()
        spec, self.solver._spec = self.solver._spec, None
        if spec is not None and spec[0] is snapshot:
            prep_f = spec[1]
        else:
            if spec is not None:
                # discarded speculation: its planned frame never ships,
                # so drop the residency prediction and re-prime (see
                # RemoteSolver.solve)
                stale = spec[1].result()
                if not stale.get("monolithic"):
                    self.solver._patch_srv = None
            prep_f = self._prep_pool.submit(self.solver._prepare_tick,
                                            snapshot)
        fut = self._rpc_pool.submit(self._run, prep_f)
        self._inflight.append(fut)
        self._gauge_depth()
        fut.add_done_callback(lambda f: self._done(f))
        return fut

    def _done(self, fut) -> None:
        try:
            self._inflight.remove(fut)
        except ValueError:
            pass
        self._gauge_depth()

    def _run(self, prep_f):
        import time as _time
        t0 = _time.perf_counter()
        prep = prep_f.result()
        waited_ms = (_time.perf_counter() - t0) * 1e3
        res = self.solver._dispatch_prepared(prep)
        if self.metrics is not None and not prep.get("monolithic"):
            # how much encode wall actually hid behind the previous
            # tick's RPC: the dispatch thread waited `waited_ms` for the
            # prepare it consumed; the rest of the encode overlapped
            self.metrics.observe(
                "karpenter_solver_pipeline_overlap_ms",
                max(0.0, prep["encode_ms"] - waited_ms))
        return res

    def solve(self, snapshot):
        """Synchronous convenience: submit and wait."""
        return self.submit(snapshot).result()

    def drain(self) -> None:
        """Wait for every outstanding tick to land."""
        while self._inflight:
            self._inflight.popleft().result()

    def close(self) -> None:
        self.drain()
        self._prep_pool.shutdown(wait=True)
        self._rpc_pool.shutdown(wait=True)
