"""Control-plane side of the solver sidecar.

``SolverClient`` speaks the raw-bytes gRPC methods; ``RemoteSolver`` is a
drop-in :class:`solver.types.Solver` whose device dispatch rides the wire
(everything else — requirements compilation, canonical ordering, decode —
is identical to the local TPU solver, so decisions are identical by
construction). Topology-constrained snapshots run the host pour locally,
exactly as TPUSolver does.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..native import arena_pack, arena_unpack
from ..solver.tpu import TPUSolver

_SOLVE = "/karpenter.solver.v1.Solver/Solve"
_INFO = "/karpenter.solver.v1.Solver/Info"


class SolverClient:
    def __init__(self, address: str, timeout: float = 30.0,
                 token: Optional[str] = None,
                 root_cert: Optional[bytes] = None):
        """`token` rides as x-solver-token metadata on every call (the
        server rejects mismatches with UNAUTHENTICATED); `root_cert`
        (PEM) switches the channel to TLS — both optional, matching the
        server's posture flags (sidecar/server.py serve())."""
        import grpc
        self.address = address
        self.timeout = timeout
        self._md = (("x-solver-token", token),) if token else None
        opts = [("grpc.max_receive_message_length", 256 * 1024 * 1024),
                ("grpc.max_send_message_length", 256 * 1024 * 1024)]
        if root_cert is not None:
            creds = grpc.ssl_channel_credentials(root_certificates=root_cert)
            self._channel = grpc.secure_channel(address, creds, options=opts)
        else:
            self._channel = grpc.insecure_channel(address, options=opts)
        self._solve = self._channel.unary_unary(_SOLVE)
        self._info = self._channel.unary_unary(_INFO)

    def solve_buffer(self, buf: np.ndarray, statics: Dict[str, int]) -> np.ndarray:
        from ..ops.hostpack import STATIC_KEYS
        req = arena_pack({
            "buf": np.ascontiguousarray(buf, dtype=np.int64),
            "statics": np.array([statics.get(k, 0) for k in STATIC_KEYS],
                                dtype=np.int64),
        })
        resp = self._solve(req, timeout=self.timeout, metadata=self._md)
        return np.array(arena_unpack(resp)["out"])  # own the memory

    def info(self, timeout: Optional[float] = None) -> Dict[str, int]:
        out = arena_unpack(self._info(b"", timeout=timeout or self.timeout,
                                      metadata=self._md))
        return {k: int(v[0]) for k, v in out.items()}

    def close(self) -> None:
        self._channel.close()


class RemoteSolver(TPUSolver):
    """TPUSolver whose packed-buffer dispatch is a sidecar round trip.

    backend='auto' (default) cost-routes each solve between the LOCAL
    host twin and the REMOTE device via the same router the in-process
    solver uses — the measured "device" cost now includes the gRPC hop,
    so deployments where the sidecar round trip dominates automatically
    stay local, and ones with a fast fabric ride the device."""

    name = "tpu-sidecar"

    def __init__(self, address: str, n_max: int = 2048,
                 client: Optional[SolverClient] = None,
                 backend: str = "auto", token: Optional[str] = None,
                 root_cert: Optional[bytes] = None):
        """`token`/`root_cert` plumb straight into SolverClient — when the
        server runs with sidecar.token / TLS, the production consumer must
        be able to authenticate (defaults also read from
        SOLVER_SIDECAR_TOKEN so the chart env reaches both containers)."""
        super().__init__(backend=backend, n_max=n_max)
        if client is None:
            if token is None:
                import os
                token = os.environ.get("SOLVER_SIDECAR_TOKEN") or None
            client = SolverClient(address, token=token, root_cert=root_cert)
        self.client = client
        from ..solver.route import AliveCache
        self._router.alive = AliveCache(self._ping)

    def _ping(self) -> bool:
        """Sidecar liveness = a short-deadline Info round trip."""
        return self.client.info(timeout=5.0)["devices"] >= 1

    def _dev_devices(self) -> int:
        """Always the packed wire dispatch: the SERVER owns the
        mesh-vs-single decision for its local devices (server.py solve)."""
        return 1

    def _topo_lowerable(self, enc, tenc, existing) -> bool:
        """Topology snapshots run the host pour locally: this solver's
        dev engine is the gRPC peer (router.alive = sidecar ping), and
        the in-process topology kernel would (a) be gated by the WRONG
        liveness verdict — a wedged local accelerator plugin hangs the
        first array creation while the sidecar ping says alive — and
        (b) feed local CPU-jax latencies into the sidecar's router
        bucket. Lowering topo solves over the wire needs a dedicated
        sidecar RPC, not a silent local detour."""
        return False

    def _dispatch(self, buf: np.ndarray, **statics) -> np.ndarray:
        return self.client.solve_buffer(buf, statics)
