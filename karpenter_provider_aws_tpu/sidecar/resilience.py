"""Resilience policy for the solver wire: retries, backoff, breaker.

Every sidecar RPC (Solve / SolvePruned / SolveTopo / Info) runs through
one :class:`ResiliencePolicy` owned by the ``SolverClient``. The policy
is what makes the <200ms p99 target survive a flaky peer: solves are
pure and the service is stateless per request (SURVEY §2.9), so a
failed or even a *duplicated* RPC is always safe to retry — the only
question is how long to keep trying before the bit-identical host twin
serves instead.

Three mechanisms, composed:

- **Per-call deadlines scaled by payload size** — a 100MB arena on a
  slow fabric legitimately needs longer than an Info ping; one flat
  timeout either kills big solves or lets small ones hang.
- **Bounded retries with exponential backoff + full jitter** — only on
  UNAVAILABLE / DEADLINE_EXCEEDED (availability-class) and on a
  malformed/truncated response arena (the codec checksum catches a
  torn write; re-asking is free). Peer *rejections* (INVALID_ARGUMENT,
  UNAUTHENTICATED, FAILED_PRECONDITION...) re-raise immediately: the
  peer answered, retrying cannot change its mind.
- **A consecutive-failure circuit breaker** — after ``threshold``
  availability failures the breaker opens and every call fails fast
  (no wire attempt) until ``cooldown_s`` elapses, then exactly one
  half-open probe rides the wire; its success closes the breaker. A
  dead sidecar must cost the provisioning loop nothing per solve, not
  a connect timeout per solve.

RESOURCE_EXHAUSTED is its own class: the server's admission layer SHED
the call (tenant over quota — sidecar/server.py _shed). The peer is
healthy, so the breaker records success, and the retry sleeps for the
server's x-retry-after-ms trailing-metadata hint instead of blind
backoff; a tenant still over quota after the retry budget sees the
real grpc error (callers degrade to the host twin, which no quota
gates).

Failure surfaces as :class:`SidecarUnavailable` (a RuntimeError, never
a ``grpc.RpcError``) so callers degrade to the host twin without
depending on grpc types.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Optional

from ..sim.clock import as_clock, monotonic_of

#: breaker states (also the value order of the state gauge: the
#: karpenter_solver_sidecar_breaker_state metric encodes closed=0,
#: half-open=1, open=2)
CLOSED = "closed"
HALF_OPEN = "half-open"
OPEN = "open"
_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

#: response-decode failures treated as availability-class: a truncated
#: or hostile response arena fails the codec checksum (ValueError) or
#: is missing fields (KeyError/IndexError); the request was fine, so
#: retrying is safe and the breaker should count the failure
_MALFORMED_RESPONSE = (ValueError, KeyError, IndexError)


class SidecarUnavailable(RuntimeError):
    """The sidecar could not serve this call (retries exhausted, or the
    breaker is open). Deliberately NOT a grpc.RpcError: the client
    contract is that no grpc error type ever escapes the policy for an
    availability failure — callers fall back to the host twin."""

    def __init__(self, rpc: str, attempts: int,
                 last_error: Optional[BaseException] = None,
                 breaker_open: bool = False):
        self.rpc = rpc
        self.attempts = attempts
        self.last_error = last_error
        self.breaker_open = breaker_open
        if breaker_open:
            msg = f"{rpc}: circuit breaker open (failing fast)"
        else:
            msg = (f"{rpc}: sidecar unavailable after {attempts} "
                   f"attempt(s): {last_error!r}")
        super().__init__(msg)


class CircuitBreaker:
    """Consecutive-failure breaker with a single half-open probe.

    closed --(threshold consecutive failures)--> open
    open --(cooldown elapsed; one call admitted)--> half-open
    half-open --(probe success)--> closed | --(probe failure)--> open

    ``on_transition`` callbacks fire OUTSIDE the lock (they park router
    EWMAs and emit metrics — both take their own locks)."""

    def __init__(self, threshold: int = 5, cooldown_s: float = 15.0,
                 clock=None):
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._clock = monotonic_of(clock)
        self._mu = threading.Lock()
        self._state = CLOSED
        self._fails = 0
        self._opened_at = 0.0
        self._probing = False
        self.on_transition: List[Callable[[str, str], None]] = []

    @property
    def state(self) -> str:
        with self._mu:
            return self._state

    def _set(self, new: str) -> Optional[tuple]:
        old = self._state
        if old == new:
            return None
        self._state = new
        return (old, new)

    def _fire(self, transition: Optional[tuple]) -> None:
        if transition is None:
            return
        for cb in list(self.on_transition):
            try:
                cb(*transition)
            except Exception:  # observers must never fail a solve
                pass

    def allow(self) -> bool:
        """May a call ride the wire right now? Open->half-open happens
        HERE: the first caller after the cooldown becomes the probe;
        concurrent callers keep failing fast until it reports."""
        with self._mu:
            if self._state == CLOSED:
                return True
            if self._state == OPEN and not self._probing \
                    and self._clock() - self._opened_at >= self.cooldown_s:
                t = self._set(HALF_OPEN)
                self._probing = True
            else:
                return False
        self._fire(t)
        return True

    def record_success(self) -> None:
        with self._mu:
            self._fails = 0
            self._probing = False
            t = self._set(CLOSED)
        self._fire(t)

    def record_failure(self) -> None:
        with self._mu:
            self._fails += 1
            self._probing = False
            t = None
            if self._state == HALF_OPEN or self._fails >= self.threshold:
                t = self._set(OPEN)
                self._opened_at = self._clock()
        self._fire(t)


class RetryPolicy:
    """Bounded retries, exponential backoff, FULL jitter (sleep drawn
    uniformly from [0, min(cap, base * 2^attempt)]) — the AWS
    architecture-blog shape that decorrelates a retry herd. ``rng`` and
    ``sleep`` are injectable so chaos tests are seeded and fast."""

    def __init__(self, max_attempts: int = 3,
                 backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 2.0,
                 rng: Optional[random.Random] = None,
                 sleep: Optional[Callable[[float], None]] = None,
                 clock=None):
        assert max_attempts >= 1
        self.max_attempts = max_attempts
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.rng = rng or random.Random()
        # an explicit sleep wins (chaos tests inject recorders); else the
        # clock seam, so a VirtualClock deschedules backoff sleeps
        self.sleep = sleep if sleep is not None else as_clock(clock).sleep

    def backoff_s(self, attempt: int) -> float:
        cap = min(self.backoff_cap_s,
                  self.backoff_base_s * (2.0 ** attempt))
        return self.rng.uniform(0.0, cap)


class ResiliencePolicy:
    """The one policy object every sidecar RPC goes through.

    ``call`` runs ``attempt_fn(deadline_s)`` (the RPC *plus* its
    response decode — a truncated arena is a failed attempt) under the
    retry policy and breaker. Observability: per-call evidence in
    ``last_call`` (bench engine reports read it) and, when ``metrics``
    is attached (controllers/telemetry.py instrument_sidecar), the
    karpenter_solver_sidecar_* series."""

    def __init__(self, retry: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 wire_bytes_per_s: float = 64 * 1024 * 1024,
                 max_deadline_s: float = 120.0,
                 metrics=None, clock=None):
        self.retry = retry or RetryPolicy(clock=clock)
        self.breaker = breaker or CircuitBreaker(clock=clock)
        self.wire_bytes_per_s = wire_bytes_per_s
        self.max_deadline_s = max_deadline_s
        self.metrics = metrics
        #: evidence from the most recent call: rpc, retries,
        #: breaker_state, ok (dispatch-evidence for bench reports)
        self.last_call: Dict = {}
        self.breaker.on_transition.append(self._emit_transition)

    # -- deadlines ------------------------------------------------------
    def deadline_for(self, payload_bytes: int, base_s: float) -> float:
        """Per-call deadline scaled by arena payload size: the base
        (the client's configured timeout) plus wire time for the
        payload at the assumed fabric bandwidth, capped."""
        extra = payload_bytes / self.wire_bytes_per_s if payload_bytes else 0.0
        return min(self.max_deadline_s, base_s + extra)

    # -- metrics --------------------------------------------------------
    def _emit_transition(self, old: str, new: str) -> None:
        m = self.metrics
        if m is not None:
            m.inc("karpenter_solver_sidecar_breaker_transitions_total",
                  labels={"from": old, "to": new})
            m.set_gauge("karpenter_solver_sidecar_breaker_state",
                        _STATE_GAUGE[new])

    def emit_state(self) -> None:
        """Seed the breaker-state gauge (called when metrics attach, so
        a scrape before the first transition still sees the series)."""
        if self.metrics is not None:
            self.metrics.set_gauge("karpenter_solver_sidecar_breaker_state",
                                   _STATE_GAUGE[self.breaker.state])

    def _record(self, rpc: str, retries: int, ok: bool,
                outcome: str) -> None:
        self.last_call = dict(rpc=rpc, retries=retries, ok=ok,
                              breaker_state=self.breaker.state)
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_sidecar_rpc_total",
                             labels={"rpc": rpc, "outcome": outcome})

    # -- the guarded call ----------------------------------------------
    def _retry_after_s(self, err, attempt: int) -> float:
        """The server's shed hint (x-retry-after-ms trailing metadata),
        capped at the backoff cap; falls back to jittered backoff when
        the peer sent no hint (old server, torn trailer)."""
        from ..tenancy.admission import RETRY_AFTER_METADATA_KEY
        try:
            for item in err.trailing_metadata() or ():
                k, v = item
                if k == RETRY_AFTER_METADATA_KEY:
                    return min(self.retry.backoff_cap_s,
                               max(0.0, float(v) / 1000.0))
        except Exception:
            pass
        return self.retry.backoff_s(attempt)

    def call(self, attempt_fn: Callable[[float], object], *, rpc: str,
             payload_bytes: int = 0, base_deadline_s: float = 30.0):
        import grpc
        retryable = (grpc.StatusCode.UNAVAILABLE,
                     grpc.StatusCode.DEADLINE_EXCEEDED)
        if not self.breaker.allow():
            self._record(rpc, 0, ok=False, outcome="breaker-open")
            raise SidecarUnavailable(rpc, 0, breaker_open=True)
        deadline = self.deadline_for(payload_bytes, base_deadline_s)
        retries = 0
        last: Optional[BaseException] = None
        for attempt in range(self.retry.max_attempts):
            try:
                out = attempt_fn(deadline)
            except grpc.RpcError as e:
                code = e.code() if hasattr(e, "code") else None
                if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                    # admission shed: the peer is HEALTHY (it answered,
                    # fast) — never count it toward the breaker, and
                    # wait the server's own hint before re-asking
                    self.breaker.record_success()
                    if attempt + 1 >= self.retry.max_attempts:
                        self._record(rpc, retries, ok=False,
                                     outcome="shed")
                        raise
                    retries += 1
                    if self.metrics is not None:
                        self.metrics.inc(
                            "karpenter_solver_sidecar_retries_total",
                            labels={"rpc": rpc})
                    self.retry.sleep(self._retry_after_s(e, attempt))
                    continue
                if code not in retryable:
                    # the peer ANSWERED (auth/validation/capability
                    # rejection): reachable, so the breaker resets; the
                    # caller sees the real grpc error and decides
                    self.breaker.record_success()
                    self._record(rpc, retries, ok=False,
                                 outcome="rejected")
                    raise
                last = e
                self.breaker.record_failure()
            except _MALFORMED_RESPONSE as e:
                last = e
                self.breaker.record_failure()
            else:
                self.breaker.record_success()
                self._record(rpc, retries, ok=True, outcome="ok")
                return out
            if attempt + 1 >= self.retry.max_attempts \
                    or self.breaker.state == OPEN:
                # out of budget, or this call's failures just opened the
                # breaker — keeping at a dead peer is what it prevents
                break
            retries += 1
            if self.metrics is not None:
                self.metrics.inc("karpenter_solver_sidecar_retries_total",
                                 labels={"rpc": rpc})
            self.retry.sleep(self.retry.backoff_s(attempt))
        self._record(rpc, retries, ok=False, outcome="unavailable")
        raise SidecarUnavailable(rpc, retries + 1, last_error=last)
