from .client import RemoteSolver, SolverClient  # noqa: F401
from .server import SolverServer, serve  # noqa: F401
