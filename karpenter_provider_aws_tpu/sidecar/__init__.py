from .client import RemoteSolver, SolverClient  # noqa: F401
from .resilience import (CircuitBreaker, ResiliencePolicy,  # noqa: F401
                         RetryPolicy, SidecarUnavailable)
from .server import SolverServer, serve  # noqa: F401
