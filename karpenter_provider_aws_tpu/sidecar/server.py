"""The JAX solver sidecar: a gRPC service the (Go-shaped) control plane
calls with one constraint-tensor arena per solve.

North star (BASELINE.json): "both provisioning bin-packing and
consolidation's multi-node replacement search run as batched jit'd
kernels called from Go via a gRPC sidecar under pkg/operator". The
service is stateless per request (SURVEY §2.9) — all solve state rides
the request arena; the only cross-request state is the XLA compilation
cache, which stays warm across solves of the same shape class exactly
like the reference's instance-type cache discipline
(instancetype.go:119-130).

Wire: raw-bytes gRPC methods (no generated stubs — the arena IS the
schema; native/codec.cpp packs/parses it on both sides):

- /karpenter.solver.v1.Solver/Solve
    request  arena: {"buf": int64[...] packed kernel inputs,
                     "statics": int64[8] = T D Z C G E P n_max}
    response arena: {"out": int64[...] packed kernel outputs}
- /karpenter.solver.v1.Solver/Info
    response arena: {"devices": int64[1], "x64": int64[1]}
"""

from __future__ import annotations

import logging
from concurrent import futures
from typing import Optional

import numpy as np

from ..native import arena_pack, arena_unpack

log = logging.getLogger(__name__)

_SOLVE = "/karpenter.solver.v1.Solver/Solve"
_INFO = "/karpenter.solver.v1.Solver/Info"


#: bounds on request statics — every distinct tuple compiles a kernel that
#: is cached for the process lifetime, so the statics space must be small
#: and sane (an unbounded space would let any peer pin the CPU compiling
#: and grow the compile cache without limit)
_STATICS_MAX = dict(T=4096, D=64, Z=64, C=8, G=1 << 17, E=1 << 14,
                    P=256, K=16, V=8192, M=1 << 16, n_max=1 << 14)
_MAX_SHAPE_CLASSES = 64


class _Handler:
    """Method implementations (bytes in, bytes out)."""

    def __init__(self):
        self._shapes_seen: set = set()

    def _validate(self, statics, buf, context) -> Optional[dict]:
        import grpc

        from ..ops.hostpack import (STATIC_KEYS, in_layout_bool,
                                    in_layout_i64, layout_sizes, nwords)
        if len(statics) == len(STATIC_KEYS) - 3:
            # pre-minValues client (8 statics: T,D,Z,C,G,E,P,n_max): the
            # floors feature is simply absent — K=V=M=0 solves identically,
            # so a rolling upgrade with the server deployed first keeps
            # serving old clients
            statics = list(statics) + [0, 0, 0]
        if len(statics) != len(STATIC_KEYS):
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"expected {len(STATIC_KEYS)} statics, "
                          f"got {len(statics)}")
        kv = dict(zip(STATIC_KEYS, (int(x) for x in statics)))
        for k, v in kv.items():
            if not (0 <= v <= _STATICS_MAX[k]):
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              f"statics.{k}={v} out of bounds")
        key = tuple(kv.values())
        if key not in self._shapes_seen:
            if len(self._shapes_seen) >= _MAX_SHAPE_CLASSES:
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                              "too many distinct solve shape classes")
            self._shapes_seen.add(key)
        dims = {k: kv[k] for k in ("T", "D", "Z", "C", "G", "E", "P",
                                   "K", "M")}
        expect = layout_sizes(in_layout_i64(**dims)) \
            + nwords(layout_sizes(in_layout_bool(**dims)))
        if buf.size != expect:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"buf size {buf.size} != layout size {expect}")
        return kv

    def solve(self, request: bytes, context) -> bytes:
        import jax
        import jax.numpy as jnp

        from ..ops.ffd_jax import solve_scan_packed1
        arrays = arena_unpack(request)
        buf = arrays["buf"]
        kv = self._validate(arrays["statics"], buf, context)
        ndev = len(jax.devices())
        if ndev > 1:
            return arena_pack({"out": self._solve_mesh(buf, kv, ndev)})
        o_buf = solve_scan_packed1(jnp.asarray(buf), **kv)
        return arena_pack({"out": np.asarray(o_buf)})

    def _solve_mesh(self, buf: np.ndarray, kv: dict,
                    ndev: int) -> np.ndarray:
        """Multi-device server: unpack the wire buffer, run the SAME
        shared mesh dispatch as the local solver (parallel/mesh.py
        dispatch_mesh), re-pack the carry into the single output buffer
        the client expects — the wire protocol is identical either way."""
        from ..ops.hostpack import pack_outputs1, unpack_inputs1
        from ..parallel.mesh import dispatch_mesh
        dims = {k: kv[k] for k in ("T", "D", "Z", "C", "G", "E", "P",
                                   "K", "M")}
        arrays = unpack_inputs1(np.asarray(buf), **dims)
        if kv["K"] == 0:
            for mk in ("mv_floor", "mv_pairs_t", "mv_pairs_v"):
                arrays.pop(mk, None)
        cache = self.__dict__.setdefault("_mesh_cache", {})
        out = dispatch_mesh(arrays, n_max=kv["n_max"], E=kv["E"],
                            P=kv["P"], V=kv["V"], ndev=ndev, cache=cache)
        return pack_outputs1(out, kv["T"], kv["D"], kv["Z"], kv["C"],
                             kv["G"], kv["E"], kv["P"], kv["n_max"])

    def info(self, request: bytes, context) -> bytes:
        import jax
        return arena_pack({
            "devices": np.array([len(jax.devices())], dtype=np.int64),
            "x64": np.array([1], dtype=np.int64),
        })


def _generic_handler(handler: _Handler):
    import grpc

    class Svc(grpc.GenericRpcHandler):
        def service(self, call_details):
            if call_details.method == _SOLVE:
                return grpc.unary_unary_rpc_method_handler(handler.solve)
            if call_details.method == _INFO:
                return grpc.unary_unary_rpc_method_handler(handler.info)
            return None

    return Svc()


def _token_interceptor(token: str):
    """Shared-secret auth: every call must carry x-solver-token metadata
    matching `token` or it is rejected UNAUTHENTICATED before the handler
    runs. Compared with hmac.compare_digest — a solver sidecar exposed
    beyond loopback must not leak the token through timing."""
    import hmac

    import grpc

    class _Auth(grpc.ServerInterceptor):
        def intercept_service(self, continuation, handler_call_details):
            md = dict(handler_call_details.invocation_metadata or ())
            got = md.get("x-solver-token", "")
            # compare as bytes: compare_digest on str raises for
            # non-ASCII, which would turn every call (correct token
            # included) into UNKNOWN instead of UNAUTHENTICATED
            if hmac.compare_digest(got.encode("utf-8", "surrogatepass"),
                                   token.encode("utf-8")):
                return continuation(handler_call_details)

            def reject(request, context):
                context.abort(grpc.StatusCode.UNAUTHENTICATED,
                              "missing or invalid x-solver-token")

            return grpc.unary_unary_rpc_method_handler(reject)

    return _Auth()


class SolverServer:
    """Owns the grpc.Server; bind with port=0 for an ephemeral port.

    Default posture is loopback + insecure (same-pod companion). Binding
    wider is an explicit decision and should come with `token` (shared
    secret) and/or `tls_cert`/`tls_key` (PEM bytes -> TLS listener) —
    the flags the deploy chart exposes under sidecar.*."""

    def __init__(self, address: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 4, token: Optional[str] = None,
                 tls_cert: Optional[bytes] = None,
                 tls_key: Optional[bytes] = None):
        import grpc
        if (tls_cert is None) != (tls_key is None):
            # a security posture must fail CLOSED: half a TLS config is
            # an operator mistake, not a request for plaintext
            raise ValueError(
                "sidecar TLS requires BOTH tls_cert and tls_key")
        interceptors = [_token_interceptor(token)] if token else []
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            interceptors=interceptors,
            options=[("grpc.max_receive_message_length", 256 * 1024 * 1024),
                     ("grpc.max_send_message_length", 256 * 1024 * 1024)])
        self._server.add_generic_rpc_handlers((_generic_handler(_Handler()),))
        if tls_cert is not None and tls_key is not None:
            creds = grpc.ssl_server_credentials(((tls_key, tls_cert),))
            self.port = self._server.add_secure_port(
                f"{address}:{port}", creds)
        else:
            self.port = self._server.add_insecure_port(f"{address}:{port}")
        self.address = f"{address}:{self.port}"

    def start(self) -> "SolverServer":
        self._server.start()
        log.info("solver sidecar listening on %s", self.address)
        return self

    def stop(self, grace: Optional[float] = 0.5) -> None:
        self._server.stop(grace)


def serve(address: str = "127.0.0.1", port: int = 50151,
          token: Optional[str] = None,
          tls_cert_file: Optional[str] = None,
          tls_key_file: Optional[str] = None) -> SolverServer:
    """Production entry: start and return the sidecar server. Defaults to
    loopback-insecure (same-pod companion). Exposing it wider is an
    explicit operator decision — pass `token` (also SOLVER_SIDECAR_TOKEN
    env) for shared-secret auth and cert/key paths for a TLS listener."""
    cert = open(tls_cert_file, "rb").read() if tls_cert_file else None
    key = open(tls_key_file, "rb").read() if tls_key_file else None
    return SolverServer(address, port, token=token,
                        tls_cert=cert, tls_key=key).start()


if __name__ == "__main__":  # pragma: no cover
    import os
    import time
    logging.basicConfig(level=logging.INFO)
    s = serve(token=os.environ.get("SOLVER_SIDECAR_TOKEN") or None,
              tls_cert_file=os.environ.get("SOLVER_SIDECAR_TLS_CERT") or None,
              tls_key_file=os.environ.get("SOLVER_SIDECAR_TLS_KEY") or None)
    while True:
        time.sleep(3600)
