"""The JAX solver sidecar: a gRPC service the (Go-shaped) control plane
calls with one constraint-tensor arena per solve.

North star (BASELINE.json): "both provisioning bin-packing and
consolidation's multi-node replacement search run as batched jit'd
kernels called from Go via a gRPC sidecar under pkg/operator". The
service is stateless per request (SURVEY §2.9) — all solve state rides
the request arena; the only cross-request state is the XLA compilation
cache, which stays warm across solves of the same shape class exactly
like the reference's instance-type cache discipline
(instancetype.go:119-130).

Wire: raw-bytes gRPC methods (no generated stubs — the arena IS the
schema; native/codec.cpp packs/parses it on both sides):

- /karpenter.solver.v1.Solver/Solve
    request  arena: {"buf": int64[...] packed kernel inputs,
                     "statics": int64[len(STATIC_KEYS)], see
                     ops/hostpack.py — appends-only, older shorter
                     vectors are padded server-side}
    response arena: {"out": int64[...] packed kernel outputs}
- /karpenter.solver.v1.Solver/SolveBatch
    request  arena: {"frame": int64[...] batch frame, see
                     ops/hostpack.py pack_batch_frame — B same-shape
                     solve buffers behind one header}
    response arena: {"out": int64[B, out_size] — row i answers item i}
- /karpenter.solver.v1.Solver/Info
    response arena: {"devices": int64[1], "x64": int64[1]}
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent import futures
from typing import Optional

import numpy as np

from ..native import arena_pack, arena_unpack
from ..sim.clock import as_clock
from ..tenancy.admission import (DEFAULT_TENANT, RETRY_AFTER_METADATA_KEY,
                                 PatchArenaTable, ShapeClassTable,
                                 tenant_from_metadata)
from ..tenancy.bucketing import bucket_statics, pad_arena, unpad_outputs
from ..tenancy.fairness import FairQueue

log = logging.getLogger(__name__)

_SOLVE = "/karpenter.solver.v1.Solver/Solve"
_SOLVE_TOPO = "/karpenter.solver.v1.Solver/SolveTopo"
_SOLVE_PRUNED = "/karpenter.solver.v1.Solver/SolvePruned"
_SOLVE_BATCH = "/karpenter.solver.v1.Solver/SolveBatch"
_SOLVE_SUBSETS = "/karpenter.solver.v1.Solver/SolveSubsets"
_SOLVE_PATCH = "/karpenter.solver.v1.Solver/SolvePatch"
_INFO = "/karpenter.solver.v1.Solver/Info"

#: arena dimensions that determine the packed-input LAYOUT — the delta
#: wire's shape-class key. n_max and V are jit statics but layout-inert,
#: so a resident arena survives n_max growth (the client's grow loop
#: redispatches the same buffer with a bigger bucket).
PATCH_LAYOUT_KEYS = ("T", "D", "Z", "C", "G", "E", "P", "K", "M", "F",
                     "Q")
#: resident patch-arena budget (each slot holds a full packed arena, so
#: the table is tighter than the shape-class table)
_MAX_PATCH_ARENAS = 32
#: resident suffix-bank budget for the incremental SolvePatch serve —
#: each slot holds a device checkpoint bank PLUS the last full output,
#: heavier than a patch arena, so the table is tighter still
_MAX_SUFFIX_BANKS = 8

#: SolvePruned statics vector order (the base-solve statics minus the
#: minValues triple — out of the pruned kernel's scope — plus S, the
#: per-step exact-slot selection width)
PRUNED_STATIC_KEYS = ("T", "D", "Z", "C", "G", "E", "P", "n_max", "S")

#: SolveTopo statics vector order (client and server share this module
#: constant via sidecar.client's import — one source of truth)
TOPO_STATIC_KEYS = ("Z", "P", "GZ", "GH", "n_max", "EVCAP", "PMAX")
_TOPO_STATICS_MAX = dict(Z=64, P=256, GZ=1 << 12, GH=1 << 12,
                         n_max=1 << 14, EVCAP=1024, PMAX=64)
#: derived-dimension bounds for SolveTopo arrays (same rationale as
#: _STATICS_MAX: every distinct shape class compiles a kernel)
_TOPO_DIM_MAX = dict(T=4096, D=64, C=8, G=1 << 13)

#: SolveSubsets statics vector order (the subset kernel's jit statics;
#: every other dimension derives from array shapes and is validated)
SUBSET_STATIC_KEYS = ("n_max", "E", "P")
#: lane-stack bounds for SolveSubsets (B lanes per round; Gq gathered
#: group rows per lane) — same compile-cache-defense rationale
_SUBSET_B_MAX = 4096
_SUBSET_GQ_MAX = 1 << 13


#: bounds on request statics — every distinct tuple compiles a kernel that
#: is cached for the process lifetime, so the statics space must be small
#: and sane (an unbounded space would let any peer pin the CPU compiling
#: and grow the compile cache without limit)
_STATICS_MAX = dict(T=4096, D=64, Z=64, C=8, G=1 << 17, E=1 << 14,
                    P=256, K=16, V=8192, M=1 << 16, n_max=1 << 14,
                    F=64, Q=1)
_MAX_SHAPE_CLASSES = 64


class _Pending:
    """One request riding the coalescing window: its packed buffer, when
    it arrived, how much of its client deadline it brought, and the slots
    the dispatching leader fills before flipping `done`."""

    __slots__ = ("buf", "arrival", "deadline_s", "out", "error", "done",
                 "wait_ms", "tenant")

    def __init__(self, buf, arrival: float, deadline_s: Optional[float],
                 tenant: str = DEFAULT_TENANT):
        self.buf = buf
        self.arrival = arrival
        self.deadline_s = deadline_s
        self.out = None
        self.error: Optional[BaseException] = None
        self.done = False
        self.wait_ms = 0.0
        self.tenant = tenant


class _Coalescer:
    """Server-side adaptive coalescing: independent single-solve RPCs
    whose statics hash to the same shape class ride ONE vmapped dispatch.

    Discipline (the deadline-safety contract the tests pin):

    - OFF at queue depth 1 — a lone request dispatches immediately, the
      window never taxes an idle server.
    - Batches form naturally while a dispatch is in flight: the per-key
      busy flag serializes dispatches, so same-shape arrivals queue
      behind the running kernel and the next leader takes them all
      (continuous batching — no artificial delay needed to reach B > 1
      under concurrent load).
    - At depth >= 2 the leader may top up with ONE bounded wait sized
      from the global inter-arrival EWMA, hard-capped at `max_window_s`
      AND at every queued request's share of its own client deadline
      (`arrival + deadline_frac * deadline`): no request ever waits past
      its share of the deadline budget it brought.
    - Per-caller demux and per-caller failure: the leader dispatches
      outside the lock; a kernel failure lands on every rider as ITS OWN
      error (each client then degrades to its host twin independently —
      the batch never takes down a caller that could have been served
      solo by its twin).
    - Fair order between tenants: each shape-class queue is a
      deficit-round-robin FairQueue (tenancy/fairness.py) keyed by the
      rider's tenant label — the leader is whoever heads the FAIR
      order, and the batch drains lanes round-robin, so one chatty
      tenant cannot keep anyone else out of a dispatch window."""

    def __init__(self, metrics=None, max_batch: int = 64,
                 deadline_frac: float = 0.25,
                 max_window_s: float = 0.025, clock=None):
        self._clock = as_clock(clock)
        self._cv = threading.Condition(threading.Lock())
        self._queues: dict = {}
        self._busy: set = set()
        self._gap_ewma: Optional[float] = None
        self._last_arrival: Optional[float] = None
        self.metrics = metrics
        self.max_batch = max_batch
        self.deadline_frac = deadline_frac
        self.max_window_s = max_window_s
        #: evidence for the bench harness: max batch dispatched and
        #: dispatch counts by mode (solo/batched)
        self.stats = {"max_batch": 0, "dispatches": 0, "batched": 0}

    def run(self, key, buf, deadline_s, dispatch_many, rpc: str,
            tenant: str = DEFAULT_TENANT):
        """Join the shape-class queue and return THIS request's output
        row. `dispatch_many([bufs]) -> [outs]` runs once per batch, on
        the leader's thread, outside the lock. ``tenant`` picks the
        fair-queue lane; the single-tenant case degenerates to the old
        FIFO exactly."""
        p = _Pending(buf, self._clock.monotonic(), deadline_s, tenant)
        batch = None
        with self._cv:
            if self._last_arrival is not None:
                gap = p.arrival - self._last_arrival
                self._gap_ewma = gap if self._gap_ewma is None \
                    else 0.3 * gap + 0.7 * self._gap_ewma
            self._last_arrival = p.arrival
            q = self._queues.setdefault(key, FairQueue())
            q.push(p, tenant)
            self._cv.notify_all()
            while not p.done:
                if key not in self._busy and q.head() is p:
                    batch = self._form_batch(key, q, rpc)
                    self._busy.add(key)
                    break
                self._clock.cond_wait(self._cv, timeout=0.05)
        if batch is not None:
            err = None
            outs = None
            try:
                outs = dispatch_many([x.buf for x in batch])
            except Exception as e:  # kernel/transport failure: per-caller
                err = e
                if self.metrics is not None:
                    self.metrics.inc(
                        "karpenter_solver_sidecar_coalesce_demux_failures"
                        "_total", len(batch), labels={"rpc": rpc})
            with self._cv:
                self._busy.discard(key)
                for i, x in enumerate(batch):
                    if err is not None:
                        x.error = err
                    else:
                        x.out = outs[i]
                    x.done = True
                if not self._queues.get(key):
                    self._queues.pop(key, None)
                self._cv.notify_all()
        if p.error is not None:
            raise p.error
        return p.out

    def _form_batch(self, key, q, rpc: str):
        """Lock held. Optionally top up (depth >= 2 only), then pop up
        to max_batch pendings IN FAIR ORDER and record the coalesce
        evidence."""
        if len(q) >= 2:
            now = self._clock.monotonic()
            window = min(2.0 * (self._gap_ewma or 0.0), self.max_window_s)
            for x in q:
                if x.deadline_s is not None:
                    share = x.arrival + self.deadline_frac * x.deadline_s
                    window = min(window, share - now)
            if window > 0:
                self._clock.cond_wait(self._cv, timeout=window)
        n = min(len(q), self.max_batch)
        batch = [q.pop() for _ in range(n)]
        t = self._clock.monotonic()
        for x in batch:
            x.wait_ms = (t - x.arrival) * 1e3
        self.stats["dispatches"] += 1
        self.stats["max_batch"] = max(self.stats["max_batch"], n)
        if n > 1:
            self.stats["batched"] += 1
        if self.metrics is not None:
            self.metrics.observe(
                "karpenter_solver_sidecar_coalesce_batch_size", n,
                labels={"rpc": rpc})
            for x in batch:
                self.metrics.observe(
                    "karpenter_solver_sidecar_coalesce_wait_ms",
                    x.wait_ms, labels={"rpc": rpc})
                self.metrics.observe(
                    "karpenter_solver_fair_queue_wait_ms",
                    x.wait_ms, labels={"rpc": rpc, "tenant": x.tenant})
            self.metrics.inc(
                "karpenter_solver_sidecar_coalesce_dispatches_total",
                labels={"rpc": rpc,
                        "mode": "batched" if n > 1 else "solo"})
        return batch


def _tenant(context) -> str:
    """The tenant label this RPC carried (x-solver-tenant metadata), or
    the anonymous default."""
    try:
        return tenant_from_metadata(context.invocation_metadata())
    except Exception:
        return DEFAULT_TENANT


def _deadline_s(context) -> Optional[float]:
    """The client deadline this RPC brought, in seconds (None when the
    peer set none) — the coalescer budgets its window from it."""
    try:
        t = context.time_remaining()
    except Exception:
        return None
    if t is None or t <= 0:
        return None
    return float(t)


class _Handler:
    """Method implementations (bytes in, bytes out).

    The executor runs four worker threads, so every piece of
    cross-request state is lock-protected: `_shapes_seen` (the
    compile-cache budget), `_mesh_cache` (the mesh dispatch's compiled
    kernels), the coalescer's queues, and the in-flight counter graceful
    stop drains on."""

    def __init__(self, metrics=None, admission=None, shape_table=None,
                 bucketing: bool = True, compile_monitor=None,
                 patch_arenas=None, mesh_group=None, clock=None):
        #: the compile-cache budget — an LRU shape-class table that
        #: still answers len()/in like the set it replaced
        self._shapes_seen = shape_table if shape_table is not None \
            else ShapeClassTable(capacity=_MAX_SHAPE_CLASSES,
                                 metrics=metrics, clock=clock)
        #: server-resident arenas for the delta wire (SolvePatch)
        self._patch_arenas = patch_arenas if patch_arenas is not None \
            else PatchArenaTable(capacity=_MAX_PATCH_ARENAS,
                                 metrics=metrics, clock=clock)
        self._admission = admission
        self._bucketing = bucketing
        self._compile_monitor = compile_monitor
        #: optional fleet.meshgroup.MeshGroup — a multi-process
        #: distributed mesh behind this server; solve paths route
        #: through it while alive and keep their local twin as the
        #: always-correct fallback
        self._mesh_group = mesh_group
        self.cache_dir = ""
        self._mesh_cache: dict = {}
        self._mesh_mu = threading.Lock()
        #: akey -> checkpoint bank for the incremental SolvePatch serve
        #: (insertion-ordered dict; oldest slot evicts at capacity).
        #: Same-akey accesses are serialized by the patch wire's version
        #: ordering; the lock only guards cross-akey insert/evict races.
        self._suffix_banks: dict = {}
        self._suffix_mu = threading.Lock()
        self._inflight = 0
        self._inflight_cv = threading.Condition(threading.Lock())
        self.metrics = metrics
        self._coalescer = _Coalescer(metrics=metrics, clock=clock)

    # -- in-flight tracking (graceful stop) -----------------------------
    def tracked(self, fn, rpc: Optional[str] = None):
        """Wrap a method handler so SolverServer.stop can drain: solves
        already past the port must land before the process exits. With
        ``rpc`` set and an admission controller configured, the wrapper
        is also the tenant gate: quota sheds answer RESOURCE_EXHAUSTED
        with a retry-after hint BEFORE any decode work happens."""
        def run(request, context):
            tenant = _tenant(context)
            admitted = False
            if rpc is not None and self._admission is not None:
                ok, reason, after = self._admission.enter(tenant, rpc)
                if not ok:
                    self._shed(context, reason, after)
                admitted = True
            with self._inflight_cv:
                self._inflight += 1
            try:
                return fn(request, context)
            finally:
                with self._inflight_cv:
                    self._inflight -= 1
                    self._inflight_cv.notify_all()
                if admitted:
                    self._admission.release(tenant)
        return run

    def _shed(self, context, reason: str, after_s: float) -> None:
        """Abort RESOURCE_EXHAUSTED with a machine-readable retry-after
        hint (trailing metadata, ms). Inflight sheds hint a short fixed
        backoff — a slot frees when any in-flight solve lands."""
        import grpc
        after_ms = max(1, int(after_s * 1000)) if after_s > 0 else 25
        try:
            context.set_trailing_metadata(
                ((RETRY_AFTER_METADATA_KEY, str(after_ms)),))
        except Exception:
            pass
        context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                      f"tenant quota exceeded ({reason}); "
                      f"retry after {after_ms}ms")

    def drain(self, timeout: Optional[float]) -> bool:
        """Block until no request is in flight (or timeout); returns
        whether the handler is idle."""
        with self._inflight_cv:
            return self._inflight_cv.wait_for(
                lambda: self._inflight == 0, timeout)

    # -- request decode / shape admission -------------------------------
    def _request_arrays(self, request: bytes, context, *required) -> dict:
        """Decode the request arena, mapping ANY decode failure —
        truncated bytes, bad checksum, missing fields — to
        INVALID_ARGUMENT. Without this a malformed payload surfaces as
        UNKNOWN, which retry policies rightly refuse to retry and
        operators read as a server bug rather than a peer bug."""
        import grpc
        try:
            arrays = arena_unpack(request)
        except Exception as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"malformed request arena: {e}")
        for k in required:
            if k not in arrays:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              f"request arena missing '{k}'")
        return arrays

    def _admit_shape(self, key, context,
                     tenant: str = DEFAULT_TENANT) -> None:
        """Spend (or re-use) a compile-cache shape-class slot. The table
        serializes internally (four workers racing unsynchronized could
        both blow the budget and corrupt it) and evicts LRU among slots
        idle past its min-idle floor — tenant churn recycles slots
        instead of wedging the server into permanent exhaustion."""
        import grpc
        if not self._shapes_seen.admit(key, tenant):
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                          "too many distinct solve shape classes")

    def _validate_statics(self, statics, context):
        """The statics half of :meth:`_validate` — bounds-check and
        normalize the statics vector without a buffer in hand (the
        patch path validates section bounds against the layout size
        before any resident bytes exist). Returns (kv, expect)."""
        import grpc

        from ..ops.hostpack import (STATIC_KEYS, in_layout_bool,
                                    in_layout_i64, layout_sizes, nwords)
        # version-skew padding by ABSOLUTE client vintage (the key count
        # each generation shipped), never len(STATIC_KEYS)-relative —
        # relative arithmetic silently re-aims at the wrong vintage every
        # time a key appends
        if len(statics) == 8:
            # pre-minValues client (8 statics: T,D,Z,C,G,E,P,n_max): the
            # floors feature is simply absent — K=V=M=0 solves identically,
            # so a rolling upgrade with the server deployed first keeps
            # serving old clients (which also predate fusion and
            # priority: F=1, Q=0)
            statics = list(statics) + [0, 0, 0, 1, 0]
        elif len(statics) == 11:
            # pre-fusion client (11 statics): its buffer carries no fuse
            # flags and F=1 runs the unfused scan, identically (Q=0:
            # also pre-priority)
            statics = list(statics) + [1, 0]
        elif len(statics) == 12:
            # pre-priority client (12 statics): the priority arena
            # section is absent — Q=0 solves identically
            statics = list(statics) + [0]
        if len(statics) != len(STATIC_KEYS):
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"expected {len(STATIC_KEYS)} statics, "
                          f"got {len(statics)}")
        kv = dict(zip(STATIC_KEYS, (int(x) for x in statics)))
        for k, v in kv.items():
            if not (0 <= v <= _STATICS_MAX[k]):
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              f"statics.{k}={v} out of bounds")
        dims = {k: kv[k] for k in ("T", "D", "Z", "C", "G", "E", "P",
                                   "K", "M", "F")}
        expect = layout_sizes(in_layout_i64(**dims)) \
            + nwords(layout_sizes(in_layout_bool(**dims)))
        return kv, expect

    def _validate(self, statics, buf, context, shape_tag=(),
                  admit: bool = True) -> Optional[dict]:
        import grpc
        kv, expect = self._validate_statics(statics, context)
        if admit:
            self._admit_shape(tuple(kv.values()) + tuple(shape_tag),
                              context, _tenant(context))
        if buf.size != expect:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"buf size {buf.size} != layout size {expect}")
        return kv

    def solve_pruned(self, request: bytes, context) -> bytes:
        """The pruned G-axis kernel over the wire (single-buffer + one
        trailing bail word, exactly the local _dispatch_pruned contract).
        Single-device servers only — the mesh path keeps the base
        kernel, so a multi-device server refuses and the client's host
        twin serves instead."""
        import grpc
        import jax
        import jax.numpy as jnp

        from ..ops.ffd_jax import solve_scan_packed1_pruned
        if len(jax.devices()) > 1:
            # precedes payload validation: a mesh server refuses the RPC
            # regardless of what was sent (clients gate on Info, so this
            # is the version-skew backstop)
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "pruned kernel is single-device; this server "
                          "runs a mesh")
        arrays = self._request_arrays(request, context, "buf", "statics")
        buf = arrays["buf"]
        statics = [int(x) for x in arrays["statics"]]
        if len(statics) != len(PRUNED_STATIC_KEYS):
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"expected {len(PRUNED_STATIC_KEYS)} statics, "
                          f"got {len(statics)}")
        S = statics[-1]
        if not (1 <= S <= 256):
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"statics.S={S} out of bounds")
        # layout/bounds validation shares the base path (K=V=M=0); the
        # shape-class key carries S + a pruned marker, since every
        # distinct S compiles its own kernel and must spend a slot of
        # the compile-cache budget like any other shape class. The
        # admitted key is the BUCKET the request pads into — near-miss
        # shapes share the slot, the kernel, and the dispatch.
        kv = self._validate(statics[:-1] + [0, 0, 0, 1], buf, context,
                            shape_tag=("pruned", S), admit=False)
        tenant = _tenant(context)
        kvB = bucket_statics(kv) if self._bucketing else kv
        self._admit_shape(tuple(kvB.values()) + ("pruned", S), context,
                          tenant)
        bufB = self._pad(np.asarray(buf), kv, kvB, context, "SolvePruned")
        dims = {k: kvB[k] for k in ("T", "D", "Z", "C", "G", "E", "P",
                                    "n_max")}

        def dispatch_many(bufs):
            if len(bufs) == 1:
                return [np.asarray(solve_scan_packed1_pruned(
                    jnp.asarray(bufs[0]), S=S, **dims))]
            from ..ops.ffd_jax import solve_scan_packed1_pruned_many
            stack = jnp.asarray(np.stack(bufs))
            return list(np.asarray(solve_scan_packed1_pruned_many(
                stack, S=S, **dims)))

        key = ("pruned", S) + tuple(kvB.values())
        o_buf = np.asarray(self._dispatch_coalesced(
            key, bufB, context, dispatch_many, "SolvePruned", tenant))
        if kvB != kv:
            # the pruned wire rides ONE trailing bail word behind the
            # packed outputs: slice around it, unpad, stitch it back
            o_buf = np.concatenate(
                [unpad_outputs(o_buf[:-1], kv, kvB), o_buf[-1:]])
        return arena_pack({"out": o_buf})

    def _dispatch_coalesced(self, key, buf, context, dispatch_many,
                            rpc: str, tenant: str = DEFAULT_TENANT):
        """Run a validated single-solve request through the coalescing
        window. A batch dispatch failure lands on every rider as its OWN
        INTERNAL abort — each client degrades to its host twin
        independently; the request that caused a bad arena never reaches
        this point (validation aborts INVALID_ARGUMENT before the queue
        join, so a malformed caller has no blast radius)."""
        import grpc
        try:
            return self._coalescer.run(key, buf, _deadline_s(context),
                                       dispatch_many, rpc=rpc,
                                       tenant=tenant)
        except Exception as e:
            context.abort(grpc.StatusCode.INTERNAL,
                          f"batched {rpc} dispatch failed: {e}")

    def _pad(self, buf: np.ndarray, kv: dict, kvB: dict, context,
             rpc: str) -> np.ndarray:
        """Pad a validated arena up to its bucket shape (no-op on a
        boundary shape). A pad failure is a server bug, not a peer bug —
        the arena already validated against kv — so it aborts INTERNAL."""
        import grpc
        if kvB == kv:
            return buf
        try:
            out = pad_arena(buf, kv, kvB)
        except Exception as e:
            context.abort(grpc.StatusCode.INTERNAL,
                          f"bucket padding failed: {e}")
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_bucket_padded_total",
                             labels={"rpc": rpc})
        return out

    def solve(self, request: bytes, context) -> bytes:
        arrays = self._request_arrays(request, context, "buf", "statics")
        buf = arrays["buf"]
        kv = self._validate(arrays["statics"], buf, context, admit=False)
        o_buf = self._solve_validated(np.asarray(buf), kv, context,
                                      _tenant(context), "Solve")
        return arena_pack({"out": o_buf})

    def _solve_validated(self, buf: np.ndarray, kv: dict, context,
                         tenant: str, rpc: str,
                         inc: Optional[dict] = None) -> np.ndarray:
        """The base-solve dispatch tail — bucket, admit, pad, coalesce,
        unpad — shared by Solve and SolvePatch so a patched resident
        arena takes EXACTLY the full-frame path from here on (the byte-
        identity argument for the delta wire rests on this sharing).

        ``inc`` (SolvePatch only) carries the arena key, the patch's
        dirty frontier, and the version pair: single-device servers then
        try the incremental serve — a suffix-only re-solve against the
        resident checkpoint bank, byte-identical by construction — and
        fall back to this shared path whenever the shape is outside the
        incremental kernel's envelope."""
        import jax
        import jax.numpy as jnp

        from ..ops.ffd_jax import solve_scan_packed1
        ndev = len(jax.devices())
        kvB = bucket_statics(kv) if self._bucketing else kv
        self._admit_shape(tuple(kvB.values()), context, tenant)
        bufB = self._pad(buf, kv, kvB, context, rpc)

        if inc is not None and ndev <= 1:
            try:
                o_inc = self._solve_incremental(bufB, kvB, inc)
            except Exception:
                # never let the incremental path take down a request the
                # shared path can serve; the bank may be mid-splice, so
                # drop it rather than risk a stale suffix later
                log.exception("incremental SolvePatch serve failed; "
                              "falling back to the shared dispatch")
                with self._suffix_mu:
                    self._suffix_banks.pop(inc["akey"], None)
                o_inc = None
            if o_inc is not None:
                return unpad_outputs(np.asarray(o_inc), kv, kvB)

        if ndev > 1:
            # mesh server: a lone request shards its ONE solve across
            # every device (2-D pods x types when the shape allows);
            # coalesced riders instead land as dp-sharded lanes of the
            # batched kernel, B/ndev per chip. Both demux byte-identical
            # to the single-device kernel, so the wire can't tell.
            def dispatch_many(bufs):
                if len(bufs) == 1:
                    return [self._solve_mesh(bufs[0], kvB, ndev)]
                return list(self._solve_batch_sharded(
                    np.stack(bufs), kvB, ndev, rpc="Solve"))
        else:
            def dispatch_many(bufs):
                if len(bufs) == 1:
                    return [np.asarray(solve_scan_packed1(
                        jnp.asarray(bufs[0]), **kvB))]
                from ..ops.ffd_jax import solve_scan_packed1_many
                stack = jnp.asarray(np.stack(bufs))
                return list(np.asarray(solve_scan_packed1_many(stack, **kvB)))

        key = ("solve", ndev) + tuple(kvB.values())
        o_buf = self._dispatch_coalesced(key, bufB, context,
                                         dispatch_many, rpc, tenant)
        return unpad_outputs(np.asarray(o_buf), kv, kvB)

    def _solve_incremental(self, bufB: np.ndarray, kvB: dict,
                           inc: dict) -> Optional[np.ndarray]:
        """Serve a SolvePatch tick from the server-resident checkpoint
        bank. When the frame's dirty frontier allows it, restore the
        deepest checkpoint at/below the frontier and re-scan only the
        suffix, splicing the suffix rows over the resident full output
        (``takes``/``leftover`` are the only group-axis outputs; every
        other field IS the final carry and comes from the suffix).
        Otherwise run the checkpointed full kernel and adopt a fresh
        bank. Returns the bucketed output buffer, or None when the
        shape is outside the incremental kernel's envelope (caller
        falls back to the shared coalesced path).

        Bank validity is version equality: a slot serves only while its
        version matches the frame's ``base_version`` — a prime, an
        interleaved full Solve, or client-side n_max growth all skew the
        pair and force a recorded full solve, never a stale suffix."""
        import jax.numpy as jnp

        from ..ops.ffd_jax import solve_scan_packed1_ckpt, solve_scan_suffix
        from ..ops.hostpack import pack_outputs1, unpack_outputs1
        from ..solver.incremental import (CKPT_CHUNK, ckpt_eligible,
                                          live_bound, suffix_plan)
        GpB = kvB["G"]
        if not ckpt_eligible(GpB, Fu=kvB.get("F", 1)):
            return None
        CK = CKPT_CHUNK
        gl = live_bound(bufB, T=kvB["T"], D=kvB["D"], G=GpB, CK=CK)
        statics = {k: v for k, v in kvB.items() if k != "F"}
        dims = {k: kvB[k] for k in ("T", "D", "Z", "C", "G", "E", "P",
                                    "n_max")}
        akey = inc["akey"]
        with self._suffix_mu:
            bank = self._suffix_banks.get(akey)
        reason = None
        if inc["base_version"] < 0 or bank is None:
            reason = "cold"
        elif bank["kvB"] != kvB or bank["GL"] != gl or gl <= 0:
            # akey pins the layout, so only the layout-inert statics
            # can differ here (n_max growth after slot exhaustion, or
            # the live bound moving under a patched tail group)
            reason = "bucket"
        elif bank["version"] != inc["base_version"]:
            reason = "version_lag"
        elif inc["frontier"] <= 0:
            reason = "frontier"
        if reason is None:
            jr, SUF = suffix_plan(min(inc["frontier"], GpB), GpB, CK,
                                  GL=gl)
            s0 = jr * CK
            sb, new_bank = solve_scan_suffix(jnp.asarray(bufB),
                                             bank["bank"], CK=CK,
                                             SUF=SUF, GL=gl, **statics)
            sv = unpack_outputs1(np.asarray(sb), **{**dims, "G": SUF * CK})
            vals = bank["vals"]
            for nm in list(vals):
                if nm in ("takes", "leftover"):
                    vals[nm][s0:gl] = sv[nm]
                else:
                    vals[nm] = sv[nm]
            bank["bank"] = new_bank
            bank["version"] = inc["new_version"]
            if self.metrics is not None:
                self.metrics.inc("karpenter_solver_solve_suffix_total",
                                 labels={"reason": "patch"})
                self.metrics.observe(
                    "karpenter_solver_solve_suffix_groups",
                    float(SUF * CK))
            return pack_outputs1(vals, **dims)
        ob, devbank = solve_scan_packed1_ckpt(jnp.asarray(bufB), CK=CK,
                                              **statics)
        o_buf = np.asarray(ob)
        # unpack a COPY: the resident vals are spliced in place on later
        # suffix ticks and must never alias the buffer already returned
        vals = unpack_outputs1(o_buf.copy(), **dims)
        with self._suffix_mu:
            self._suffix_banks.pop(akey, None)
            while len(self._suffix_banks) >= _MAX_SUFFIX_BANKS:
                self._suffix_banks.pop(next(iter(self._suffix_banks)))
            self._suffix_banks[akey] = dict(kvB=dict(kvB), GL=gl,
                                            version=inc["new_version"],
                                            bank=devbank, vals=vals)
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_solve_full_total",
                             labels={"reason": reason})
        return o_buf

    def solve_patch(self, request: bytes, context) -> bytes:
        """The delta wire: apply dirty word sections against the
        server-resident arena for (tenant, layout shape, client token,
        arena epoch), then run the base-solve tail on the patched
        buffer. Three frame kinds share the wire format:

        - prime (base_version < 0): one full-coverage section installs
          (or replaces) the resident arena
        - delta: disjoint ascending sections advance base -> new version
        - clean resend (no sections): re-solve the resident arena as-is

        Any miss or version skew aborts FAILED_PRECONDITION and the
        client degrades to ONE full Solve; a rejected prime (table full
        of hot arenas) still solves, replying resident=0 so the client
        keeps full-framing without error noise."""
        import grpc

        from ..ops.hostpack import frontier_from_sections, unpack_patch_frame
        arrays = self._request_arrays(request, context, "frame")
        try:
            hdr, svec, sections, payloads = unpack_patch_frame(
                np.asarray(arrays["frame"]))
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"malformed patch frame: {e}")
        kv, expect = self._validate_statics(svec, context)
        if sections and sections[-1][1] > expect:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"patch section beyond arena "
                          f"({sections[-1][1]} > {expect})")
        tenant = _tenant(context)
        akey = (tenant, tuple(kv[k] for k in PATCH_LAYOUT_KEYS),
                hdr["token"], hdr["epoch"])
        if hdr["base_version"] < 0:
            if len(sections) != 1 or sections[0] != (0, expect):
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              "prime frame must cover the whole arena")
            buf = np.asarray(payloads[0])
            resident = self._patch_arenas.prime(
                akey, buf, hdr["new_version"], tenant)
            frontier = 0
        else:
            buf, reason = self._patch_arenas.apply(
                akey, sections, payloads, hdr["base_version"],
                hdr["new_version"])
            if buf is None:
                context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                              "no resident arena" if reason ==
                              "no_resident" else "stale arena version")
            resident = True
            # the server-side dirty frontier, recovered purely from the
            # patched word sections (no new wire field): the incremental
            # serve may resume the scan from the deepest checkpoint at
            # or below it. Empty sections (clean resend) -> G.
            frontier = frontier_from_sections(
                sections, **{k: kv[k] for k in ("T", "D", "Z", "C", "G",
                                                "E", "P", "K", "M", "F",
                                                "Q")})
        # a rejected prime keeps the client full-framing, so a bank
        # recorded for it could never be reused — skip the serve
        inc = dict(akey=akey, frontier=frontier,
                   base_version=hdr["base_version"],
                   new_version=hdr["new_version"]) if resident else None
        o_buf = self._solve_validated(buf, kv, context, tenant,
                                      "SolvePatch", inc=inc)
        return arena_pack({
            "out": o_buf,
            "resident": np.array([1 if resident else 0], dtype=np.int64),
            "version": np.array([hdr["new_version"]], dtype=np.int64)})

    def solve_batch(self, request: bytes, context) -> bytes:
        """B same-shape solves in ONE round trip: validate the batch
        frame, dispatch the vmapped kernel once, reply with the stacked
        [B, out_size] rows. Unlike SolvePruned this IS served on a mesh
        server — jit(vmap) runs on the default device and decides
        identically, so version skew never changes decisions."""
        import grpc
        import jax
        import jax.numpy as jnp

        from ..ops.ffd_jax import solve_scan_packed1, solve_scan_packed1_many
        from ..ops.hostpack import STATIC_KEYS, unpack_batch_frame
        arrays = self._request_arrays(request, context, "frame")
        try:
            statics, bufs = unpack_batch_frame(arrays["frame"])
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"malformed batch frame: {e}")
        B = len(bufs)
        # every distinct B compiles its own vmapped kernel, so B joins
        # the shape-class key and spends a compile-cache slot
        kv = self._validate([statics[k] for k in STATIC_KEYS], bufs[0],
                            context, shape_tag=("batch", B))
        for i in range(1, B):
            if bufs[i].size != bufs[0].size:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              f"batch item {i} size {bufs[i].size} != "
                              f"item 0 size {bufs[0].size}")
        ndev = len(jax.devices())
        if B == 1:
            o = np.asarray(solve_scan_packed1(jnp.asarray(bufs[0]),
                                              **kv))[None, :]
        elif ndev > 1:
            o = self._solve_batch_sharded(np.stack(bufs), kv, ndev)
        else:
            stack = jnp.asarray(np.stack(bufs))
            o = np.asarray(solve_scan_packed1_many(stack, **kv))
        if self.metrics is not None:
            self.metrics.observe(
                "karpenter_solver_sidecar_coalesce_batch_size", B,
                labels={"rpc": "SolveBatch"})
            self.metrics.inc(
                "karpenter_solver_sidecar_coalesce_dispatches_total",
                labels={"rpc": "SolveBatch", "mode": "frame"})
        return arena_pack({"out": o})

    def _mesh_alive(self) -> bool:
        """The mesh-group gate every serving path consults. Doubles as
        the supervisor's wiring into the request plane: while the
        group sits degraded, each consult kicks the scheduled regroup
        (fleet/meshgroup.py heal_async — a no-op until the backoff
        deadline, and never blocking this RPC)."""
        if self._mesh_group is None:
            return False
        alive = self._mesh_group.alive()
        if not alive:
            self._mesh_group.heal_async()
        return alive

    def _solve_batch_sharded(self, stack: np.ndarray, kv: dict, ndev: int,
                             rpc: str = "SolveBatch") -> np.ndarray:
        """Run a stacked [B, W] batch with the B axis dp-sharded across
        the server's devices (parallel/mesh.py shard_batch): B/ndev
        independent lanes per chip, zero cross-device collectives,
        results byte-identical to the single-device vmapped kernel."""
        from ..ops.ffd_jax import solve_scan_packed1_many
        from ..parallel.mesh import shard_batch
        B = stack.shape[0]
        if self._mesh_alive():
            # distributed group: lanes fan out across processes, each
            # solved on that worker's local devices (linear scale-out,
            # zero collectives). None/raise keeps the local path — the
            # group degrades itself, decisions are identical either way
            try:
                out = self._mesh_group.solve_batch(stack, kv)
            except Exception:
                out = None
            if out is not None:
                if self.metrics is not None:
                    self.metrics.inc(
                        "karpenter_solver_mesh_batch_lanes_total",
                        B, labels={"rpc": rpc})
                return np.asarray(out)[:B]
        with self._mesh_mu:
            d_stack, _ = shard_batch(stack, ndev, self._mesh_cache)
        out = np.asarray(solve_scan_packed1_many(d_stack, **kv))[:B]
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_mesh_batch_lanes_total",
                             B, labels={"rpc": rpc})
        return out

    def _solve_mesh(self, buf: np.ndarray, kv: dict,
                    ndev: int) -> np.ndarray:
        """Multi-device server: unpack the wire buffer, run the SAME
        shared mesh dispatch as the local solver (parallel/mesh.py
        dispatch_mesh), re-pack the carry into the single output buffer
        the client expects — the wire protocol is identical either way."""
        from ..ops.hostpack import pack_outputs1, unpack_inputs1
        from ..parallel.mesh import dispatch_mesh
        dims = {k: kv[k] for k in ("T", "D", "Z", "C", "G", "E", "P",
                                   "K", "M", "F", "Q")}
        arrays = unpack_inputs1(np.asarray(buf), **dims)
        # a fusion-requesting client (F>1, single-device RemoteSolver)
        # may still land on a mesh server: the flags are advisory — the
        # mesh scan stays per-group and decides identically. Likewise
        # the priority vector (Q=1): decisions are priority-blind, the
        # mesh arena walk stays Q-free
        arrays.pop("fuse", None)
        arrays.pop("prio", None)
        if kv["K"] == 0:
            for mk in ("mv_floor", "mv_pairs_t", "mv_pairs_v"):
                arrays.pop(mk, None)
        # distributed group first: the 2-D solve's slot axis spans every
        # process, each worker committing only its dp slab (frame mode —
        # the arena arrived whole over gRPC, so the coordinator slices).
        # dist is dp2-only: minValues floors (K>0) and flex lanes (V>0)
        # stay on the local 1-D type mesh
        if self._mesh_alive() and kv["K"] == 0 and kv["V"] == 0:
            try:
                with self._mesh_mu:
                    r = self._mesh_group.solve_frame(
                        arrays, {k: kv[k] for k in ("n_max", "E", "P")},
                        want_arrays=True)
                if r.get("out"):
                    return pack_outputs1(
                        r["out"], kv["T"], kv["D"], kv["Z"], kv["C"],
                        kv["G"], kv["E"], kv["P"], kv["n_max"])
            except Exception:
                log.exception("mesh group solve failed; serving from "
                              "the local mesh")
        # dispatch_mesh reads AND writes its compile cache; serialize
        # mesh solves — they already contend for every device, so the
        # lock costs nothing beyond what the hardware imposes
        with self._mesh_mu:
            out = dispatch_mesh(arrays, n_max=kv["n_max"], E=kv["E"],
                                P=kv["P"], V=kv["V"], ndev=ndev,
                                cache=self._mesh_cache,
                                metrics=self.metrics)
        return pack_outputs1(out, kv["T"], kv["D"], kv["Z"], kv["C"],
                             kv["G"], kv["E"], kv["P"], kv["n_max"])

    def solve_topo(self, request: bytes, context) -> bytes:
        """Topology event-kernel solve over the wire: 'i_*' arrays are
        KernelInputs fields, 't_*' arrays are TopoGroupRows fields,
        'statics' is the TOPO_STATIC_KEYS vector. The shared
        ops/topo_jax.dispatch_topo implementation serves both this RPC
        and the local solver, so the two paths cannot drift."""
        import grpc

        from ..ops.topo_jax import dispatch_topo
        all_arrays = self._request_arrays(request, context)
        raw = all_arrays.get("statics")
        if raw is None or len(raw) != len(TOPO_STATIC_KEYS):
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"expected {len(TOPO_STATIC_KEYS)} topo statics")
        kv = dict(zip(TOPO_STATIC_KEYS, (int(x) for x in raw)))
        for k, v in kv.items():
            if not (0 <= v <= _TOPO_STATICS_MAX[k]):
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              f"statics.{k}={v} out of bounds")
        arrays = {k[2:]: v for k, v in all_arrays.items()
                  if k.startswith("i_")}
        rows = {k[2:]: v for k, v in all_arrays.items()
                if k.startswith("t_")}
        self._validate_topo(arrays, rows, kv, context)
        # dtypes are canonical after validation, so shapes + statics
        # fully determine the compiled kernel; C rides via avail_zc
        key = ("topo",) + tuple(kv.values()) + (
            arrays["A"].shape, arrays["avail_zc"].shape,
            arrays["R"].shape[0])
        self._admit_shape(key, context, _tenant(context))
        out = dispatch_topo(arrays, rows, kv)
        return arena_pack({k: np.asarray(v) for k, v in out.items()})

    def _validate_topo(self, arrays, rows, kv, context) -> None:
        """Every array shape must agree with the dims the request
        implies — a peer must not be able to shape-shift the kernel into
        unbounded compiles or out-of-bounds gathers."""
        import grpc

        def fail(msg):
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, msg)

        try:
            T, D = arrays["A"].shape
            G = arrays["R"].shape[0]
            C = arrays["agc"].shape[1]
        except (KeyError, ValueError, IndexError, AttributeError):
            fail("missing/odd core arrays (A, R, agc)")
        Z, P = kv["Z"], kv["P"]
        GZ, GH = kv["GZ"], kv["GH"]
        for name, bound in (("T", _TOPO_DIM_MAX["T"]),
                            ("D", _TOPO_DIM_MAX["D"]),
                            ("C", _TOPO_DIM_MAX["C"]),
                            ("G", _TOPO_DIM_MAX["G"])):
            if not (0 < {"T": T, "D": D, "C": C, "G": G}[name] <= bound):
                fail(f"dim {name} out of bounds")
        # (shape, dtype-class) per array: 'i' = int64, 'b' = bool/uint8,
        # 'i32' = int32. Dtype enforcement is part of the compile-cache
        # defense — a peer varying dtypes at fixed shapes would mint
        # unbounded kernels past the shape-class budget otherwise.
        expect_i = dict(
            A=((T, D), "i"), avail_zc=((T, Z * C), "b"),
            R=((G, D), "i"), n=((G,), "i"), F=((G, T), "b"),
            agz=((G, Z), "b"), agc=((G, C), "b"), admit=((G, P), "b"),
            daemon=((G, P, D), "i"),
            pool_types=((P, T), "b"), pool_agz=((P, Z), "b"),
            pool_agc=((P, C), "b"), pool_limit=((P, D), "i"),
            pool_used0=((P, D), "i"),
            ex_alloc=((0, D), "i"), ex_used0=((0, D), "i"),
            ex_compat=((G, 0), "b"))
        expect_t = dict(
            has_topo=((G,), "b"), zone_needed=((G,), "b"),
            min_mask=((G, Z), "b"),
            zs_any=((G, GZ), "b"), zs_skew=((G, GZ), "i"),
            hs_any=((G, GH), "b"), hs_skew=((G, GH), "i"),
            za_any=((G, GZ), "b"), za_anti=((G, GZ), "b"),
            za_own=((G, GZ), "b"), ha_any=((G, GH), "b"),
            ha_anti=((G, GH), "b"), ha_own=((G, GH), "b"),
            member_z=((G,), "i32"), member_h=((G,), "i32"))
        ok_dtypes = {"i": (np.dtype(np.int64),),
                     "b": (np.dtype(bool), np.dtype(np.uint8)),
                     "i32": (np.dtype(np.int32),)}
        for table, got in ((expect_i, arrays), (expect_t, rows)):
            if set(table) != set(got):
                fail(f"array set mismatch: {sorted(set(table) ^ set(got))}")
            for name, (shape, kind) in table.items():
                if tuple(got[name].shape) != shape:
                    fail(f"{name} shape {got[name].shape} != {shape}")
                if got[name].dtype not in ok_dtypes[kind]:
                    fail(f"{name} dtype {got[name].dtype} not allowed")

    def solve_subsets(self, request: bytes, context) -> bytes:
        """Whole-fleet consolidation subset search over the wire: 'i_*'
        arrays are the shared union-arena KernelInputs fields (ONE arena
        for every lane — the payload does not scale with the candidate
        count), 'q_*' arrays are the per-lane index/mask stacks,
        'tprice' the per-type cheapest prices, 'statics' the
        SUBSET_STATIC_KEYS vector. The shared
        ops/consolidation_jax.subset_solve_kernel implementation serves
        both this RPC and the local solver, so the two paths cannot
        drift; the reply is the [B, 5] SUBSET_OUT_COLS summary."""
        import grpc

        import jax.numpy as jnp

        from ..ops.consolidation_jax import subset_solve_kernel
        all_arrays = self._request_arrays(request, context)
        raw = all_arrays.get("statics")
        if raw is None or len(raw) != len(SUBSET_STATIC_KEYS):
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"expected {len(SUBSET_STATIC_KEYS)} "
                          "subset statics")
        kv = dict(zip(SUBSET_STATIC_KEYS, (int(x) for x in raw)))
        arrays = {k[2:]: v for k, v in all_arrays.items()
                  if k.startswith("i_")}
        lanes = {k[2:]: v for k, v in all_arrays.items()
                 if k.startswith("q_")}
        tprice = all_arrays.get("tprice")
        self._validate_subsets(arrays, lanes, tprice, kv, context)
        key = ("subsets",) + tuple(kv.values()) + (
            arrays["A"].shape, arrays["avail_zc"].shape,
            arrays["R"].shape[0], tuple(lanes["gid"].shape))
        self._admit_shape(key, context, _tenant(context))

        def b(a):  # uint8 wire bools -> kernel bool
            return jnp.asarray(np.asarray(a, dtype=bool))

        out = subset_solve_kernel(
            jnp.asarray(arrays["A"]), b(arrays["avail_zc"]),
            jnp.asarray(tprice),
            jnp.asarray(arrays["R"]), jnp.asarray(arrays["n"]),
            b(arrays["F"]), b(arrays["agz"]), b(arrays["agc"]),
            b(arrays["admit"]), jnp.asarray(arrays["daemon"]),
            b(arrays["ex_compat"]), b(arrays["pool_types"]),
            b(arrays["pool_agz"]), b(arrays["pool_agc"]),
            jnp.asarray(arrays["pool_limit"]),
            jnp.asarray(arrays["pool_used0"]),
            jnp.asarray(arrays["ex_alloc"]),
            jnp.asarray(arrays["ex_used0"]),
            jnp.asarray(lanes["gid"]), jnp.asarray(lanes["n"]),
            b(lanes["dead"]), b(lanes["keep"]),
            jnp.asarray(lanes["price"]),
            n_max=kv["n_max"], E=kv["E"], P=kv["P"])
        return arena_pack({"out": np.asarray(out)})

    def _validate_subsets(self, arrays, lanes, tprice, kv,
                          context) -> None:
        """Every array shape must agree with the dims the request
        implies (same defense as _validate_topo): no shape-shifting the
        kernel into unbounded compiles or out-of-bounds gathers."""
        import grpc

        def fail(msg):
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, msg)

        try:
            T, D = arrays["A"].shape
            G = arrays["R"].shape[0]
            Z = arrays["agz"].shape[1]
            C = arrays["agc"].shape[1]
            ZC = arrays["avail_zc"].shape[1]
            B, Gq = lanes["gid"].shape
        except (KeyError, ValueError, IndexError, AttributeError):
            fail("missing/odd core arrays (A, R, agz, agc, gid)")
        E, P, n_max = kv["E"], kv["P"], kv["n_max"]
        dims = dict(T=T, D=D, Z=Z, C=C, G=G, E=E, P=P, n_max=n_max)
        for name, val in dims.items():
            lo = 0 if name == "E" else 1
            if not (lo <= val <= _STATICS_MAX[name]):
                fail(f"dim {name} out of bounds")
        if not (1 <= B <= _SUBSET_B_MAX):
            fail("dim B out of bounds")
        if not (1 <= Gq <= _SUBSET_GQ_MAX):
            fail("dim Gq out of bounds")
        if ZC != Z * C:
            fail("avail_zc width != Z*C")
        expect_i = dict(
            A=((T, D), "i"), avail_zc=((T, ZC), "b"),
            R=((G, D), "i"), n=((G,), "i"), F=((G, T), "b"),
            agz=((G, Z), "b"), agc=((G, C), "b"), admit=((G, P), "b"),
            daemon=((G, P, D), "i"),
            pool_types=((P, T), "b"), pool_agz=((P, Z), "b"),
            pool_agc=((P, C), "b"), pool_limit=((P, D), "i"),
            pool_used0=((P, D), "i"),
            ex_alloc=((E, D), "i"), ex_used0=((E, D), "i"),
            ex_compat=((G, E), "b"))
        expect_q = dict(
            gid=((B, Gq), "i32"), n=((B, Gq), "i"),
            dead=((B, E), "b"), keep=((B, T), "b"), price=((B,), "i"))
        ok_dtypes = {"i": (np.dtype(np.int64),),
                     "b": (np.dtype(bool), np.dtype(np.uint8)),
                     "i32": (np.dtype(np.int32),)}
        for table, got in ((expect_i, arrays), (expect_q, lanes)):
            if set(table) != set(got):
                fail(f"array set mismatch: {sorted(set(table) ^ set(got))}")
            for name, (shape, kind) in table.items():
                if tuple(got[name].shape) != shape:
                    fail(f"{name} shape {got[name].shape} != {shape}")
                if got[name].dtype not in ok_dtypes[kind]:
                    fail(f"{name} dtype {got[name].dtype} not allowed")
        if tprice is None or tuple(tprice.shape) != (T,) \
                or tprice.dtype != np.dtype(np.int64):
            fail("tprice must be int64 [T]")
        # gather safety: jax clamps out-of-range indices, and a clamped
        # row is a wrong answer, not an error — reject it at the door
        if int(np.asarray(lanes["gid"]).max(initial=0)) >= G \
                or int(np.asarray(lanes["gid"]).min(initial=0)) < 0:
            fail("gid out of range")

    def info(self, request: bytes, context) -> bytes:
        import jax
        cc = self._compile_monitor.counts() if self._compile_monitor \
            else {"hits": 0, "misses": 0}
        return arena_pack({
            "devices": np.array([len(jax.devices())], dtype=np.int64),
            "x64": np.array([1], dtype=np.int64),
            # capability flag: clients gate SolvePruned on it, so an
            # old server (no flag) simply never receives the RPC
            "pruned": np.array([1], dtype=np.int64),
            # same gating discipline for the multi-arena SolveBatch
            # frame (served on mesh servers too — jit(vmap) runs on the
            # default device and decides identically)
            "batch": np.array([1], dtype=np.int64),
            # whole-fleet consolidation subset search (SolveSubsets)
            "subsets": np.array([1], dtype=np.int64),
            # delta wire: dirty-section patches against a server-
            # resident arena (SolvePatch) — same gating discipline
            "patch": np.array([1], dtype=np.int64),
            # tenancy surface: whether admission quotas are enforced,
            # whether near-miss shapes ride bucketed padding, and the
            # persistent compile cache's hit/miss counts since start —
            # the warm-start acceptance check reads these two counters
            "tenancy": np.array(
                [1 if self._admission is not None else 0], dtype=np.int64),
            "bucketed": np.array([1 if self._bucketing else 0],
                                 dtype=np.int64),
            # multi-process distributed mesh behind this server
            # (fleet/meshgroup.py); drops to 0 on degrade and returns
            # to 1 after a supervised regroup, so fleet membership sees
            # the capability change on its next probe — the Info
            # consult itself kicks a due regroup (_mesh_alive)
            "mesh_group": np.array([1 if self._mesh_alive() else 0],
                                   dtype=np.int64),
            # the group's formation epoch (0 = no group): operators can
            # watch it step to count regroups from Info alone
            "mesh_epoch": np.array(
                [self._mesh_group.epoch
                 if self._mesh_group is not None else 0],
                dtype=np.int64),
            "compile_cache_hits": np.array([cc["hits"]], dtype=np.int64),
            "compile_cache_misses": np.array([cc["misses"]],
                                             dtype=np.int64),
        })


def _generic_handler(handler: _Handler):
    import grpc

    class Svc(grpc.GenericRpcHandler):
        def service(self, call_details):
            # every method rides the in-flight tracker so graceful stop
            # can drain solves already past the port
            # solve RPCs name themselves to the tracker so the tenant
            # admission gate runs; Info stays quota-exempt (it is the
            # capability/health probe — shedding it would blind clients)
            if call_details.method == _SOLVE:
                return grpc.unary_unary_rpc_method_handler(
                    handler.tracked(handler.solve, rpc="Solve"))
            if call_details.method == _SOLVE_TOPO:
                return grpc.unary_unary_rpc_method_handler(
                    handler.tracked(handler.solve_topo, rpc="SolveTopo"))
            if call_details.method == _SOLVE_PRUNED:
                return grpc.unary_unary_rpc_method_handler(
                    handler.tracked(handler.solve_pruned,
                                    rpc="SolvePruned"))
            if call_details.method == _SOLVE_BATCH:
                return grpc.unary_unary_rpc_method_handler(
                    handler.tracked(handler.solve_batch,
                                    rpc="SolveBatch"))
            if call_details.method == _SOLVE_SUBSETS:
                return grpc.unary_unary_rpc_method_handler(
                    handler.tracked(handler.solve_subsets,
                                    rpc="SolveSubsets"))
            if call_details.method == _SOLVE_PATCH:
                return grpc.unary_unary_rpc_method_handler(
                    handler.tracked(handler.solve_patch,
                                    rpc="SolvePatch"))
            if call_details.method == _INFO:
                return grpc.unary_unary_rpc_method_handler(
                    handler.tracked(handler.info))
            return None

    return Svc()


def _token_interceptor(token: str):
    """Shared-secret auth: every call must carry x-solver-token metadata
    matching `token` or it is rejected UNAUTHENTICATED before the handler
    runs. Compared with hmac.compare_digest — a solver sidecar exposed
    beyond loopback must not leak the token through timing."""
    import hmac

    import grpc

    class _Auth(grpc.ServerInterceptor):
        def intercept_service(self, continuation, handler_call_details):
            md = dict(handler_call_details.invocation_metadata or ())
            got = md.get("x-solver-token", "")
            # compare as bytes: compare_digest on str raises for
            # non-ASCII, which would turn every call (correct token
            # included) into UNKNOWN instead of UNAUTHENTICATED
            if hmac.compare_digest(got.encode("utf-8", "surrogatepass"),
                                   token.encode("utf-8")):
                return continuation(handler_call_details)

            def reject(request, context):
                context.abort(grpc.StatusCode.UNAUTHENTICATED,
                              "missing or invalid x-solver-token")

            return grpc.unary_unary_rpc_method_handler(reject)

    return _Auth()


class SolverServer:
    """Owns the grpc.Server; bind with port=0 for an ephemeral port.

    Default posture is loopback + insecure (same-pod companion). Binding
    wider is an explicit decision and should come with `token` (shared
    secret) and/or `tls_cert`/`tls_key` (PEM bytes -> TLS listener) —
    the flags the deploy chart exposes under sidecar.*."""

    def __init__(self, address: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 4, token: Optional[str] = None,
                 tls_cert: Optional[bytes] = None,
                 tls_key: Optional[bytes] = None, metrics=None,
                 quotas: Optional[dict] = None,
                 default_quota=None, bucketing: bool = True,
                 compile_cache: bool = True,
                 compile_cache_dir: Optional[str] = None,
                 aot_cache: bool = True, aot_record: bool = False,
                 mesh_workers: Optional[int] = None, clock=None):
        import grpc
        if (tls_cert is None) != (tls_key is None):
            # a security posture must fail CLOSED: half a TLS config is
            # an operator mistake, not a request for plaintext
            raise ValueError(
                "sidecar TLS requires BOTH tls_cert and tls_key")
        interceptors = [_token_interceptor(token)] if token else []
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            interceptors=interceptors,
            options=[("grpc.max_receive_message_length", 256 * 1024 * 1024),
                     ("grpc.max_send_message_length", 256 * 1024 * 1024)])
        # tenancy: quotas map tenant -> TenantQuota; default_quota
        # covers unlisted tenants. Neither set (the default) keeps the
        # permissive pre-tenancy posture — nothing sheds, nothing new
        # to operate. Bucketed padding and the persistent compile cache
        # are on by default; both have env escape hatches in serve().
        admission = None
        if quotas or default_quota is not None:
            from ..tenancy.admission import AdmissionController
            admission = AdmissionController(
                quotas=quotas, default_quota=default_quota,
                metrics=metrics, clock=clock)
        monitor = None
        cache_dir = ""
        if compile_cache:
            from ..tenancy.compilecache import (CompileCacheMonitor,
                                                configure_compile_cache,
                                                pin_host_isa)
            # before any jax backend touch: XLA:CPU codegen stays within
            # what THIS host's CPUID can verify, so no cache entry ever
            # carries an unverifiable machine feature (the cpu_aot_loader
            # mismatch warning from the MULTICHIP r05 log)
            pin_host_isa()
            cache_dir = configure_compile_cache(compile_cache_dir)
            monitor = CompileCacheMonitor(metrics=metrics)
        if aot_cache:
            from ..tenancy.compilecache import activate_aot
            store = activate_aot(record=aot_record,
                                 root=compile_cache_dir, metrics=metrics)
            n = store.preload()
            if n:
                log.info("aot store: %d executable(s) resident from %s",
                         n, store.path)
            # kick the device-liveness probe NOW (nonblocking): the
            # store is consulted on the dev dispatch path only, and a
            # probe still pending at the first RPC would send that
            # solve to the host twin — the exact cold-start latency
            # the primed store exists to eliminate
            from ..solver.route import device_alive_nonblocking
            device_alive_nonblocking()
        # metrics: optional utils.metrics.Metrics registry; the coalesce
        # families (docs/metrics.md) are emitted through it when present
        if metrics is not None:
            # native host-twin engagement (deltawalk/patch/frame) rides
            # the same registry — last attach wins, one per process
            from ..native import deltawalk as _dwalk
            _dwalk.attach_metrics(metrics)
        # distributed mesh group: SOLVER_DISTMESH_WORKERS extra worker
        # processes joined into one logical dp x tp solver (explicit
        # arg wins over env). Formed BEFORE the first RPC so clients
        # never observe the capability flapping on at runtime
        self._mesh_group = None
        if mesh_workers is None:
            import os as _os

            from ..parallel.distmesh import (LOCAL_DEVICES_ENV,
                                             WORKERS_ENV)
            mesh_workers = int(_os.environ.get(WORKERS_ENV, "0") or 0)
            mesh_local = int(_os.environ.get(LOCAL_DEVICES_ENV, "8")
                             or 8)
        else:
            mesh_local = 8
        if mesh_workers > 0:
            from ..fleet.meshgroup import MeshGroup
            self._mesh_group = MeshGroup(
                workers=mesh_workers, local_devices=mesh_local,
                metrics=metrics).start()
        self._handler = _Handler(metrics=metrics, admission=admission,
                                 bucketing=bucketing,
                                 compile_monitor=monitor,
                                 mesh_group=self._mesh_group,
                                 clock=clock)
        self._handler.cache_dir = cache_dir
        self._server.add_generic_rpc_handlers(
            (_generic_handler(self._handler),))
        if tls_cert is not None and tls_key is not None:
            creds = grpc.ssl_server_credentials(((tls_key, tls_cert),))
            self.port = self._server.add_secure_port(
                f"{address}:{port}", creds)
        else:
            self.port = self._server.add_insecure_port(f"{address}:{port}")
        self.address = f"{address}:{self.port}"

    def start(self) -> "SolverServer":
        self._server.start()
        log.info("solver sidecar listening on %s", self.address)
        return self

    def stop(self, grace: Optional[float] = 0.5) -> None:
        """Graceful stop: new RPCs are refused immediately (grpc stop
        semantics), then in-flight solves get the grace window to LAND
        before the hard cancel — a solve already past the port must not
        be torn mid-kernel by a rolling restart."""
        done = self._server.stop(grace)
        drained = self._handler.drain(grace)
        if not drained:
            log.warning("sidecar stop: in-flight solves still running "
                        "after %.1fs grace; cancelling", grace or 0.0)
        done.wait(grace)
        if self._mesh_group is not None:
            self._mesh_group.stop()


def serve(address: str = "127.0.0.1", port: int = 50151,
          token: Optional[str] = None,
          tls_cert_file: Optional[str] = None,
          tls_key_file: Optional[str] = None,
          quotas: Optional[dict] = None,
          default_quota=None) -> SolverServer:
    """Production entry: start and return the sidecar server. Defaults to
    loopback-insecure (same-pod companion). Exposing it wider is an
    explicit operator decision — pass `token` (also SOLVER_SIDECAR_TOKEN
    env) for shared-secret auth and cert/key paths for a TLS listener.
    Tenancy knobs ride the environment for the __main__ entry:
    SOLVER_SIDECAR_BUCKETING=0 disables bucketed padding,
    SOLVER_SIDECAR_COMPILE_CACHE=0 the persistent compile cache
    (dir: KARPENTER_JAX_CACHE), SOLVER_SIDECAR_AOT=0 the AOT executable
    store (primed via `make aot-prime`; SOLVER_SIDECAR_AOT_RECORD=1
    records cold shape classes in-process), SOLVER_SIDECAR_DEFAULT_QUOTA=
    "rate,burst,inflight" a fleet-wide per-tenant quota."""
    import os
    cert = key = None
    if tls_cert_file:
        with open(tls_cert_file, "rb") as f:
            cert = f.read()
    if tls_key_file:
        with open(tls_key_file, "rb") as f:
            key = f.read()
    if default_quota is None:
        raw = os.environ.get("SOLVER_SIDECAR_DEFAULT_QUOTA")
        if raw:
            from ..tenancy.admission import TenantQuota
            parts = [p.strip() for p in raw.split(",")]
            default_quota = TenantQuota(
                rate=float(parts[0]) if parts[0] else None,
                burst=int(parts[1]) if len(parts) > 1 and parts[1]
                else None,
                max_inflight=int(parts[2]) if len(parts) > 2 and parts[2]
                else None)
    return SolverServer(
        address, port, token=token, tls_cert=cert, tls_key=key,
        quotas=quotas, default_quota=default_quota,
        bucketing=os.environ.get("SOLVER_SIDECAR_BUCKETING", "1") != "0",
        compile_cache=os.environ.get(
            "SOLVER_SIDECAR_COMPILE_CACHE", "1") != "0",
        aot_cache=os.environ.get("SOLVER_SIDECAR_AOT", "1") != "0",
        aot_record=os.environ.get(
            "SOLVER_SIDECAR_AOT_RECORD", "0") == "1").start()


if __name__ == "__main__":  # pragma: no cover
    import os
    import time
    logging.basicConfig(level=logging.INFO)
    # fleet replicas (chart: sidecar.replicaCount, a StatefulSet behind
    # a headless Service) listen beyond loopback — an explicit env
    # opt-in, same posture as token/TLS
    s = serve(address=os.environ.get("SOLVER_SIDECAR_LISTEN",
                                     "127.0.0.1"),
              port=int(os.environ.get("SOLVER_SIDECAR_PORT", "50151")),
              token=os.environ.get("SOLVER_SIDECAR_TOKEN") or None,
              tls_cert_file=os.environ.get("SOLVER_SIDECAR_TLS_CERT") or None,
              tls_key_file=os.environ.get("SOLVER_SIDECAR_TLS_KEY") or None)
    while True:
        time.sleep(3600)
