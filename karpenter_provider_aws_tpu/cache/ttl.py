"""TTL caches + the UnavailableOfferings ICE blacklist.

TTL constants mirror pkg/cache/cache.go:19-55; UnavailableOfferings mirrors
pkg/cache/unavailableofferings.go:33-86 — keyed (capacityType:instanceType:
zone), 3-minute TTL, with a seqnum so it participates in the instance-type
provider's cache key (a blacklist change must invalidate resolved catalogs).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Hashable, Optional, Tuple

from ..sim.clock import monotonic_of

# cache.go:19-55
DEFAULT_TTL = 60.0
UNAVAILABLE_OFFERINGS_TTL = 3 * 60.0
INSTANCE_TYPES_ZONES_TTL = 5 * 60.0
INSTANCE_PROFILE_TTL = 15 * 60.0
AVAILABLE_IPS_TTL = 5 * 60.0
SSM_TTL = 24 * 3600.0
DISCOVERED_CAPACITY_TTL = 60 * 24 * 3600.0


class TTLCache:
    """A thread-safe TTL cache with injectable clock (tests and the
    endurance simulator control time): ``clock`` is a bare ``()->float``
    callable or a :class:`~..sim.clock.Clock`."""

    def __init__(self, ttl: float = DEFAULT_TTL, clock=None):
        self.ttl = ttl
        self._clock = monotonic_of(clock)
        self._mu = threading.RLock()
        self._data: Dict[Hashable, Tuple[float, Any]] = {}

    def get(self, key: Hashable) -> Optional[Any]:
        with self._mu:
            hit = self._data.get(key)
            if hit is None:
                return None
            expiry, value = hit
            if self._clock() >= expiry:
                del self._data[key]
                return None
            return value

    def put(self, key: Hashable, value: Any, ttl: Optional[float] = None) -> None:
        with self._mu:
            self._data[key] = (self._clock() + (ttl if ttl is not None else self.ttl), value)

    def delete(self, key: Hashable) -> None:
        with self._mu:
            self._data.pop(key, None)

    def clear(self) -> None:
        with self._mu:
            self._data.clear()

    def keys(self):
        with self._mu:
            now = self._clock()
            return [k for k, (exp, _) in self._data.items() if now < exp]

    def flush_expired(self) -> int:
        with self._mu:
            now = self._clock()
            dead = [k for k, (exp, _) in self._data.items() if now >= exp]
            for k in dead:
                del self._data[k]
            return len(dead)

    def __len__(self) -> int:
        return len(self.keys())


class UnavailableOfferings:
    """ICE-aware offering blacklist (unavailableofferings.go:33-86).

    The launcher marks (capacityType, instanceType, zone) pools here on
    InsufficientInstanceCapacity; the instance-type provider consults it when
    building offerings so the next Solve round avoids the pools; entries
    expire after 3 minutes. ``seqnum`` bumps on every change for cache-key
    participation.
    """

    def __init__(self, clock=None,
                 ttl: float = UNAVAILABLE_OFFERINGS_TTL):
        self._cache = TTLCache(ttl=ttl, clock=clock)
        self._mu = threading.Lock()
        self.seqnum = 0

    @staticmethod
    def _key(capacity_type: str, instance_type: str, zone: str) -> str:
        return f"{capacity_type}:{instance_type}:{zone}"

    def mark_unavailable(self, capacity_type: str, instance_type: str,
                         zone: str, reason: str = "InsufficientInstanceCapacity") -> None:
        with self._mu:
            self._cache.put(self._key(capacity_type, instance_type, zone), reason)
            self.seqnum += 1

    def mark_available_after_expiry(self) -> None:
        """Expiry is lazy (reads check the clock); bump seqnum when anything
        lapsed so dependent caches rebuild."""
        with self._mu:
            if self._cache.flush_expired():
                self.seqnum += 1

    def is_unavailable(self, capacity_type: str, instance_type: str, zone: str) -> bool:
        return self._cache.get(self._key(capacity_type, instance_type, zone)) is not None

    def delete(self, capacity_type: str, instance_type: str, zone: str) -> None:
        with self._mu:
            self._cache.delete(self._key(capacity_type, instance_type, zone))
            self.seqnum += 1
