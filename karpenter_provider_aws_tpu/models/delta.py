"""Incremental (dirty-set) snapshot encoding: resident arenas patched
between solves.

Reconcile ticks re-solve snapshots that are ~99% identical to the last
one (a few pods bound, one node launched), yet ``encode_snapshot`` is
oblivious: it re-derives every group gather, pool tensor and
existing-node table from scratch, and at the 50k-pod envelope that host
encode is the single largest serial share of the solve. The
``DeltaEncoder`` keeps the last solve's ``SnapshotEncoding`` (and
existing-node tables) RESIDENT and classifies each new snapshot against
it:

- ``hit``    — nothing the tensors depend on changed: the resident
  encoding is returned as-is; encode cost is the diff walk alone.
- ``rows``   — same signature set, same structure: only per-group pod
  membership/counts, pool in-use/limit vectors, or existing-node tables
  moved. The resident arrays are patched IN PLACE (``n[i]``, pool
  vectors, existing tables); every signature-derived tensor (R/F/agz/
  agc/admit/daemon/minValues/topo) is untouched — same signature set
  plus same structural universe makes them provably identical.
- ``groups`` — the signature SET changed (new deployment shape, a group
  fully bound, preference relaxation): the group axis is rebuilt via
  ``encode_snapshot`` riding the warm signature row bank, and
  existing-node compat rows are REMAPPED by signature from the resident
  matrix instead of recomputed, when the node set is unchanged.
- ``full``   — structural change (catalog/pool/daemon/zone objects, and
  with them possibly the label universe, dims, or statics shape): the
  resident state is discarded and rebuilt from scratch. ``epoch`` bumps
  so arena-coherent caches (consolidation's base tables) refresh.

Oracle discipline: every returned encoding must be ARRAY-FOR-ARRAY
byte-identical to a from-scratch ``encode_snapshot`` of the same
snapshot (and the existing tables to ``full_existing_encode``); the
fuzz suite (tests/test_delta_encoding.py) asserts exactly that at every
mutation step, so decisions stay fingerprint-identical by construction.

Staleness discipline — the same one _CATALOG_CACHE and _RowBank
already rely on: catalog/pool/daemon changes arrive as NEW objects
(providers hand out stable objects until a seqnum bump), so structure
is diffed by OBJECT IDENTITY, while pods and existing nodes are diffed
by content (signature tuples, member identity; node label/taint/
resource values — state/cluster.py rebuilds those objects every tick).
The residency pins the previous snapshot's pod lists and pool/daemon
objects, so a recycled id can never alias a live key. Pool in-use and
limit vectors sit OUTSIDE the identity contract (in_use moves every
tick on the same spec shape) and are therefore recomputed and compared
every solve via the shared ``pool_dynamic_vecs`` derivation.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..native import deltawalk as _dw
from ..solver.types import ExistingNode, SchedulingSnapshot
from .encoding import (SnapshotEncoding, canonical_pod_groups,
                       encode_snapshot, pool_dynamic_vecs)


@dataclass
class SnapshotDelta:
    """What changed vs the last-encoded snapshot — and how the encode
    was served. ``tier`` is the solver's honesty marker
    (``last_phase_stats["cache"]``); the dirty flags drive the packed-
    arena patch on the device wire (solver/tpu.py ``_patch_pack_cache``:
    a clean flag means the resident packed section is still valid)."""
    tier: str                      # hit | rows | groups | full
    reason: str = ""               # full only: cold|disabled|structural-*
    #: group rows + existing-node columns patched/recomputed this encode
    patched_rows: int = 0
    groups_changed: int = 0
    pods_added: int = 0
    pods_removed: int = 0
    nodes_added: int = 0
    nodes_removed: int = 0
    nodes_changed: int = 0
    n_dirty: bool = False          # enc.n moved
    pools_dirty: bool = False      # pool limit/in-use vectors moved
    ex_rows_dirty: bool = False    # ex_alloc/ex_used moved (or E changed)
    ex_compat_dirty: bool = False  # ex_compat moved (or E changed)
    prio_dirty: bool = False       # enc.prio moved (group priorities)
    #: minimum canonical group index whose ROW moved this encode (count,
    #: membership, or priority) — the incremental-solve resume bound: the
    #: scan carry entering group i depends only on groups < i, so a
    #: checkpointed solve may resume at or below this index. 0 (resume
    #: from scratch = the existing full solve) whenever anything OUTSIDE
    #: the group axis moved (pools, existing rows/compat, node set) or
    #: the tier is not rows — the conservative fallback IS the oracle.
    #: G (nothing moved) is possible on hit-tier encodes.
    dirty_frontier: int = 0

    def dirty_fields(self) -> Tuple[List[str], List[str]]:
        """The dirty flags as kernel-input field names, (int64 fields,
        bool fields) in arena layout order — the single vocabulary shared
        by the packed-arena patch (solver/tpu.py _patch_pack_cache) and
        the mesh resident-arena patch (parallel/mesh.py _place_resident).
        A field NOT listed is guaranteed byte-identical to the previous
        encode, so its resident copy (packed section or sharded device
        buffer) stays valid."""
        d64: List[str] = []
        db: List[str] = []
        if self.n_dirty:
            d64.append("n")
        if self.pools_dirty:
            d64 += ["pool_limit", "pool_used0"]
        if self.ex_rows_dirty:
            d64 += ["ex_alloc", "ex_used0"]
        if self.prio_dirty:
            d64.append("prio")
        if self.ex_compat_dirty:
            db.append("ex_compat")
        return d64, db


def structural_key(snapshot: SchedulingSnapshot) -> Tuple:
    """Identity key of everything that shapes the encoding's universe:
    nodepool objects + their resolved catalogs IN SNAPSHOT ORDER (the
    union catalog's variant numbering is first-seen order), daemon
    overhead objects, and the zone map. Any difference here can move
    the label universe, dims, or statics shape — the explicit
    "structural change -> full re-encode" fallback."""
    return (
        tuple((id(spec.nodepool),) + tuple(id(t) for t in spec.instance_types)
              for spec in snapshot.nodepools),
        tuple(id(d) for d in snapshot.daemon_overheads),
        tuple(sorted(snapshot.zones.items())),
        # PriorityClass CONTENT (not identity): a value edit or a new
        # class changes every resolved pod priority without changing any
        # pool/daemon object — a stale resident arena would keep serving
        # old priorities
        tuple(sorted(
            (pc.metadata.name, pc.value, pc.global_default,
             pc.preemption_policy)
            for pc in getattr(snapshot, "priority_classes", ()))),
    )


def _skey_diff(old: Tuple, new: Tuple) -> str:
    for part, name in zip(range(4),
                          ("pools", "daemons", "zones", "priority")):
        if part < len(old) and part < len(new) and old[part] != new[part]:
            return name
    return "pools"


def _ex_rows(enc: SnapshotEncoding, existing: Sequence[ExistingNode]):
    """[E, D] allocatable / used tables. O(E x D) — always recomputed
    fresh (node ``used`` moves every tick); the delta path only diffs
    the RESULT to decide whether the packed arena section is dirty."""
    E, D = len(existing), len(enc.dims)
    dpos = {d: i for i, d in enumerate(enc.dims)}
    ex_alloc = np.zeros((E, D), dtype=np.int64)
    ex_used = np.zeros((E, D), dtype=np.int64)
    for ei, node in enumerate(existing):
        for k, q in node.allocatable.items():
            i = dpos.get(k)
            if i is not None:
                ex_alloc[ei, i] = q
        for k, q in node.used.items():
            i = dpos.get(k)
            if i is not None:
                ex_used[ei, i] = q
    return ex_alloc, ex_used


def _compat_col(groups, node: ExistingNode) -> np.ndarray:
    """[G] bool — which groups may land on this node (labels + taints).
    A pure function of (signature, node labels/taints): the delta path
    caches these columns per node and recomputes only when the node's
    token (labels + taints content) moves."""
    col = np.zeros(len(groups), dtype=bool)
    for g in groups:
        pod = g.pods[0]
        col[g.index] = (g.reqs.satisfied_by_labels(node.labels)
                        and all(t.tolerated_by(pod.tolerations)
                                for t in node.taints))
    return col


def full_existing_encode(enc: SnapshotEncoding,
                         existing: Sequence[ExistingNode]):
    """From-scratch (ex_alloc, ex_used, ex_compat) — the existing-node
    oracle every delta path must match byte-for-byte."""
    ex_alloc, ex_used = _ex_rows(enc, existing)
    ex_compat = np.zeros((len(enc.groups), len(existing)), dtype=bool)
    for ei, node in enumerate(existing):
        ex_compat[:, ei] = _compat_col(enc.groups, node)
    return ex_alloc, ex_used, ex_compat


def _node_token(node: ExistingNode) -> Tuple:
    """Content token guarding compat-column reuse. COPIES, not
    references: a caller mutating a reused node object in place must
    invalidate the column, which an aliased dict could never detect."""
    return (dict(node.labels), tuple(node.taints))


class DeltaEncoder:
    """Resident-arena incremental encoder (see module docstring).

    One instance per solver; not thread-safe (solvers are single-
    threaded per instance — the sidecar server gives each session its
    own). ``encode`` is a drop-in for ``encode_snapshot`` +
    ``full_existing_encode`` that additionally returns the
    ``SnapshotDelta`` classification."""

    def __init__(self):
        #: resident state: the last encoding and its derivation inputs
        self._enc: Optional[SnapshotEncoding] = None
        self._sigs: Tuple = ()
        self._skey: Optional[Tuple] = None
        #: pins for the id()-keyed structural diff (same discipline as
        #: _RowBank.pins: a GC'd pool whose address is recycled for a
        #: NEW pool must never alias the old key)
        self._pins: Tuple = ()
        self._dpos: Dict[str, int] = {}
        self._ex_names: List[str] = []
        self._ex_tok: Dict[str, Tuple] = {}
        self._ex_alloc: Optional[np.ndarray] = None
        self._ex_used: Optional[np.ndarray] = None
        self._ex_compat: Optional[np.ndarray] = None
        #: bumps on every STRUCTURAL rebuild — the invalidation edge for
        #: caches keyed by catalog/pool object identity (consolidation's
        #: base tables): identity keys stay valid across hit/rows/groups
        #: encodes, and exactly stop being valid when structure moves
        self.epoch = 0
        #: bumps whenever the returned arrays differ from the previous
        #: encode's (any dirty flag, or a new encoding object). The
        #: packed-arena cache (solver/tpu.py) records the version its
        #: buffer reflects; lagging more than one version (e.g. host-
        #: served solves in between) forces a re-pack instead of a patch
        self.version = 0
        self.last_delta: Optional[SnapshotDelta] = None
        #: optional metrics registry (the solver forwards its own)
        self.metrics = None

    # -- public entry --------------------------------------------------
    def state_token(self) -> Tuple[int, int]:
        """(epoch, version) snapshot — the coherence key the delta wire
        and speculative pre-encode compare against: equal tokens mean
        the encoder's arrays are exactly the ones a caller captured."""
        return (self.epoch, self.version)

    def encode(self, snapshot: SchedulingSnapshot, pod_groups,
               existing: Sequence[ExistingNode]):
        """(enc, (ex_alloc, ex_used, ex_compat), SnapshotDelta) for this
        snapshot. ``existing`` must be the name-sorted node list the
        solver decodes against (sorted once, shared)."""
        if pod_groups is None:
            pod_groups = canonical_pod_groups(snapshot.pods)
        if self._enc is None:
            return self._full(snapshot, pod_groups, existing, "cold", False)
        skey = structural_key(snapshot)
        if skey != self._skey:
            reason = "structural-" + _skey_diff(self._skey, skey)
            return self._full(snapshot, pod_groups, existing, reason, True)
        sigs = tuple(s for s, _ in pod_groups)
        if sigs != self._sigs:
            return self._tier_groups(snapshot, pod_groups, existing)
        return self._tier_rows(snapshot, pod_groups, existing)

    # -- tiers ---------------------------------------------------------
    def _full(self, snapshot, pod_groups, existing, reason: str,
              structural: bool):
        enc = encode_snapshot(snapshot, pod_groups=pod_groups)
        ex = full_existing_encode(enc, existing)
        self._adopt(snapshot, enc, pod_groups, existing, ex)
        if structural:
            self.epoch += 1
        self.version += 1
        d = SnapshotDelta(tier="full", reason=reason, n_dirty=True,
                          pools_dirty=True, ex_rows_dirty=True,
                          ex_compat_dirty=True,
                          prio_dirty=enc.prio is not None)
        self.last_delta = d
        m = self.metrics
        if m is not None:
            m.inc("karpenter_solver_encode_full_total",
                  labels={"reason": reason})
            if structural:
                m.inc("karpenter_solver_encode_fallback_total",
                      labels={"reason": reason[len("structural-"):]})
        return enc, ex, d

    def _tier_groups(self, snapshot, pod_groups, existing):
        """Signature set changed under a stable structural universe: the
        group-axis rebuild rides the warm signature row bank inside
        ``encode_snapshot`` (recurring sigs skip the requirements
        algebra), and resident existing-compat ROWS are remapped by
        signature — a compat row is a pure function of (sig, node
        token), so an unchanged node set keeps every recurring sig's
        row."""
        old_enc, old_compat = self._enc, self._ex_compat
        old_row = {g.sig: g.index for g in old_enc.groups}
        enc = encode_snapshot(snapshot, pod_groups=pod_groups)
        ex_alloc, ex_used = _ex_rows(enc, existing)
        names = [n.name for n in existing]
        E, G = len(existing), len(enc.groups)
        remap_ok = (old_compat is not None and names == self._ex_names
                    and all(self._ex_tok.get(n.name) == _node_token(n)
                            for n in existing))
        ex_compat = np.zeros((G, E), dtype=bool)
        new_rows = 0
        if remap_ok:
            for g in enc.groups:
                oi = old_row.get(g.sig)
                if oi is None:
                    if E:
                        for ei, node in enumerate(existing):
                            pod = g.pods[0]
                            ex_compat[g.index, ei] = (
                                g.reqs.satisfied_by_labels(node.labels)
                                and all(t.tolerated_by(pod.tolerations)
                                        for t in node.taints))
                    new_rows += 1
                else:
                    ex_compat[g.index] = old_compat[oi]
        else:
            for ei, node in enumerate(existing):
                ex_compat[:, ei] = _compat_col(enc.groups, node)
            new_rows = G
        self._adopt(snapshot, enc, pod_groups, existing,
                    (ex_alloc, ex_used, ex_compat))
        self.version += 1
        d = SnapshotDelta(tier="groups", patched_rows=new_rows,
                          groups_changed=abs(G - len(old_row)) or 1,
                          n_dirty=True, pools_dirty=True,
                          ex_rows_dirty=True, ex_compat_dirty=True,
                          prio_dirty=enc.prio is not None)
        self.last_delta = d
        if self.metrics is not None:
            self.metrics.inc("karpenter_solver_encode_delta_total",
                             labels={"tier": "groups"})
        return enc, (ex_alloc, ex_used, ex_compat), d

    def _tier_rows(self, snapshot, pod_groups, existing):
        """Same signature set, same structural universe: the canonical
        group order is a pure function of the signature set, so group
        positions align with the resident encoding and every signature-
        derived tensor is already correct. Patch what can move: pod
        membership/counts, pool dynamic vectors, existing-node tables."""
        enc = self._enc
        d = SnapshotDelta(tier="hit", dirty_frontier=len(pod_groups))
        n = enc.n
        for i, (_sig, plist) in enumerate(pod_groups):
            g = enc.groups[i]
            old = g.pods
            if old is plist:
                continue
            if len(old) == len(plist) and \
                    all(a is b for a, b in zip(old, plist)):
                # same members behind a rebuilt list: adopt silently so
                # the identity fast path stays warm next tick
                g.pods = plist
                continue
            # the loop ascends canonical order, so the FIRST dirty group
            # is the min — membership churn counts even when the count
            # is unchanged (conservative: the row's bytes may not move,
            # the frontier still drops)
            d.dirty_frontier = min(d.dirty_frontier, i)
            d.groups_changed += 1
            d.pods_added += max(0, len(plist) - len(old))
            d.pods_removed += max(0, len(old) - len(plist))
            g.pods = plist
            if n[i] != len(plist):
                n[i] = len(plist)
                d.n_dirty = True
        # pool dynamic vectors: recomputed every tick (in_use sits
        # outside the object-identity staleness contract) through the
        # SAME derivation encode_snapshot uses, then diffed. The diff
        # and the patch are ONE native pass (compare + copy-where-
        # different) when the deltawalk library serves — the resident
        # vector keeps its identity, so nothing downstream re-alloates.
        use_native = _dw.enabled()
        if use_native:
            _dw.record_engaged("deltawalk")
        else:
            _dw.record_fallback(_dw.fallback_reason())
        dpos = self._dpos
        D = len(enc.dims)
        ordered = sorted(
            snapshot.nodepools,
            key=lambda s: (-s.nodepool.weight, s.nodepool.metadata.name))
        for pe, spec in zip(enc.pools, ordered):
            lim, iu = pool_dynamic_vecs(spec, D, dpos)
            moved = _dw.diff_patch_i64(pe.in_use_vec, iu) \
                if use_native else None
            if moved is None:
                if not np.array_equal(iu, pe.in_use_vec):
                    pe.in_use_vec = iu
                    d.pools_dirty = True
            elif moved:
                d.pools_dirty = True
            if (lim is None) != (pe.limit_vec is None) or (
                    lim is not None
                    and not np.array_equal(lim, pe.limit_vec)):
                pe.limit_vec = lim
                d.pools_dirty = True
            pe.spec = spec
        self._patch_existing(enc, existing, d)
        d.patched_rows = (d.groups_changed + d.nodes_added
                          + d.nodes_changed)
        if (d.pools_dirty or d.ex_rows_dirty or d.ex_compat_dirty
                or d.nodes_added or d.nodes_removed or d.nodes_changed):
            # node-side dirtiness feeds the scan's initial carry (pool
            # vectors, existing rows) or every step (compat): no prefix
            # checkpoint survives it
            d.dirty_frontier = 0
        if (d.groups_changed or d.n_dirty or d.pools_dirty
                or d.ex_rows_dirty or d.ex_compat_dirty
                or d.nodes_added or d.nodes_removed or d.nodes_changed):
            d.tier = "rows"
        if (d.n_dirty or d.pools_dirty or d.ex_rows_dirty
                or d.ex_compat_dirty):
            self.version += 1
        self.last_delta = d
        m = self.metrics
        if m is not None:
            m.inc("karpenter_solver_encode_delta_total",
                  labels={"tier": d.tier})
            if d.patched_rows:
                m.observe("karpenter_solver_encode_patched_rows",
                          float(d.patched_rows))
        return enc, (self._ex_alloc, self._ex_used, self._ex_compat), d

    # -- existing-node residency ---------------------------------------
    def _patch_existing(self, enc, existing, d: SnapshotDelta):
        ex_alloc, ex_used = _ex_rows(enc, existing)
        moved = None
        if _dw.enabled():
            # one native pass: diff against the RESIDENT tables and
            # patch them where they differ, preserving their identity
            # (the packed-arena cache repacks straight from them)
            ra = _dw.diff_patch_i64(self._ex_alloc, ex_alloc)
            ru = _dw.diff_patch_i64(self._ex_used, ex_used) \
                if ra is not None else None
            if ru is not None:
                moved = bool(ra or ru)
        if moved is None:
            if not (np.array_equal(ex_alloc, self._ex_alloc)
                    and np.array_equal(ex_used, self._ex_used)):
                d.ex_rows_dirty = True
            self._ex_alloc, self._ex_used = ex_alloc, ex_used
        elif moved:
            d.ex_rows_dirty = True
        names = [n.name for n in existing]
        tok = self._ex_tok
        if names == self._ex_names:
            for ei, node in enumerate(existing):
                if tok.get(node.name) == _node_token(node):
                    continue
                self._ex_compat[:, ei] = _compat_col(enc.groups, node)
                tok[node.name] = _node_token(node)
                d.nodes_changed += 1
                d.ex_compat_dirty = True
            return
        # node set moved: rebuild the matrix, reusing unchanged columns
        old_idx = {nm: i for i, nm in enumerate(self._ex_names)}
        G, E = len(enc.groups), len(existing)
        new_compat = np.zeros((G, E), dtype=bool)
        new_tok: Dict[str, Tuple] = {}
        for ei, node in enumerate(existing):
            oi = old_idx.get(node.name)
            t = _node_token(node)
            if oi is not None and tok.get(node.name) == t:
                new_compat[:, ei] = self._ex_compat[:, oi]
            else:
                new_compat[:, ei] = _compat_col(enc.groups, node)
                if oi is None:
                    d.nodes_added += 1
                else:
                    d.nodes_changed += 1
            new_tok[node.name] = t
        d.nodes_removed = sum(1 for nm in self._ex_names
                              if nm not in new_tok)
        self._ex_compat, self._ex_tok = new_compat, new_tok
        self._ex_names = names
        d.ex_compat_dirty = True

    # -- residency bookkeeping -----------------------------------------
    def _adopt(self, snapshot, enc, pod_groups, existing, ex):
        self._enc = enc
        self._sigs = tuple(s for s, _ in pod_groups)
        self._skey = structural_key(snapshot)
        self._pins = (tuple(s.nodepool for s in snapshot.nodepools),
                      tuple(tuple(s.instance_types)
                            for s in snapshot.nodepools),
                      tuple(snapshot.daemon_overheads))
        self._dpos = {dd: i for i, dd in enumerate(enc.dims)}
        self._ex_names = [n.name for n in existing]
        self._ex_tok = {n.name: _node_token(n) for n in existing}
        self._ex_alloc, self._ex_used, self._ex_compat = ex
