"""Constraint-tensor encoding: SchedulingSnapshot -> dense arrays.

This is the lowering from the requirements algebra (apis/requirements.py)
to the tensors the TPU kernels consume — the "model" of this framework.

Encoding scheme
---------------
- Pods dedup to **groups** (equal ``pod_group_signature``), ordered by the
  canonical FFD order (solver/cpu.py::pod_sort_key). Group-batched FFD is
  exactly per-pod FFD because the canonical order keeps groups contiguous
  within a size class.
- The **label universe** interns every (key, value) pair appearing in
  instance-type requirements (minus zone / zone-id / capacity-type, which
  ride the offerings tensors). Each type stores one value id per key
  (ABSENT when undefined); each group stores a boolean allow-mask per key
  (complement sets and Gt/Lt bounds evaluated against the interned values
  at encode time). Type-level feasibility is then K gathered mask lookups:
      F[g, t] = AND_k mask[g, k, type_val[t, k]]
- Zones and capacity types are tiny enumerations: offering availability is
  ``avail[T, Z, C]`` with fixed-point prices ``price[T, Z, C]`` (int64
  micro-USD; unavailable = PRICE_INF). Group/pool zone and capacity-type
  requirements become allow-vectors ``agz[*, Z]`` / ``agc[*, C]``.
- Resources are exact int64 (millicores / bytes) — the fit comparison is
  bit-identical to the CPU oracle's, by construction.

Everything host-side here is numpy; jax arrays are produced at the boundary
by solver/tpu.py.
"""

from __future__ import annotations

import operator
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Set, Tuple

import numpy as np

import threading

from ..apis import labels as L
from ..apis.objects import Pod
from ..apis.requirements import Requirement, Requirements
from ..apis.resources import Resources
from ..cloudprovider.types import InstanceType
from ..solver.cpu import pod_group_signature, pod_sig_digest, pod_sort_key
from ..solver.types import NodePoolSpec, SchedulingSnapshot

PRICE_INF = np.int64(1) << 60
ABSENT = 0  # value id 0 of every key means "label absent on the type"

CAPACITY_TYPES = (L.CAPACITY_TYPE_ON_DEMAND, L.CAPACITY_TYPE_SPOT,
                  L.CAPACITY_TYPE_RESERVED)

#: keys that ride the offerings tensors instead of the label universe
_OFFERING_KEYS = frozenset({L.ZONE, L.ZONE_ID, L.CAPACITY_TYPE})


class LabelUniverse:
    """Interns (key, value) pairs from instance-type requirement sets."""

    def __init__(self, types: Sequence[InstanceType]):
        keys: Set[str] = set()
        for t in types:
            for r in t.requirements:
                if r.key not in _OFFERING_KEYS:
                    keys.add(r.key)
        self.keys: List[str] = sorted(keys)
        self.key_pos = {k: i for i, k in enumerate(self.keys)}
        # value id 0 reserved for ABSENT
        self.values: List[Dict[str, int]] = [dict() for _ in self.keys]
        self.value_names: List[List[str]] = [["<absent>"] for _ in self.keys]
        for t in types:
            for r in t.requirements:
                ki = self.key_pos.get(r.key)
                if ki is None:
                    continue
                for v in r.values:
                    self._intern(ki, v)
        # numeric value per (key, id) for Gt/Lt evaluation (None -> NaN)
        self.numeric: List[np.ndarray] = []
        for ki in range(len(self.keys)):
            arr = np.full(len(self.value_names[ki]), np.nan)
            for v, vid in self.values[ki].items():
                try:
                    arr[vid] = int(v)
                except ValueError:
                    pass
            self.numeric.append(arr)

    def _intern(self, ki: int, v: str) -> int:
        vid = self.values[ki].get(v)
        if vid is None:
            vid = len(self.value_names[ki])
            self.values[ki][v] = vid
            self.value_names[ki].append(v)
        return vid

    def n_values(self, ki: int) -> int:
        return len(self.value_names[ki])

    def type_value_ids(self, types: Sequence[InstanceType]) -> np.ndarray:
        """[T, K] int32 — each type's value id per key (ABSENT if undefined
        or if the type's requirement on the key isn't a single concrete
        value; DoesNotExist maps to ABSENT)."""
        out = np.zeros((len(types), len(self.keys)), dtype=np.int32)
        for ti, t in enumerate(types):
            for r in t.requirements:
                ki = self.key_pos.get(r.key)
                if ki is None:
                    continue
                if not r.complement and len(r.values) == 1:
                    out[ti, ki] = self.values[ki][next(iter(r.values))]
                # DoesNotExist (empty, non-complement) stays ABSENT
        return out

    def requirement_mask(self, req: Requirement) -> np.ndarray:
        """Allow-mask over the key's value ids (index 0 = ABSENT)."""
        ki = self.key_pos[req.key]
        n = self.n_values(ki)
        mask = np.zeros(n, dtype=bool)
        for vid in range(1, n):
            if req.has(self.value_names[ki][vid]):
                mask[vid] = True
        mask[ABSENT] = req.satisfied_by_absence()
        return mask

    def group_masks(self, reqs: Requirements) -> Dict[int, np.ndarray]:
        """key index -> allow-mask, only for keys the reqs constrain."""
        out = {}
        for r in reqs:
            if r.key in _OFFERING_KEYS:
                continue
            ki = self.key_pos.get(r.key)
            if ki is not None:
                out[ki] = self.requirement_mask(r)
        return out


class PodGroup:
    """One scheduling-signature group (plain __slots__ class: the
    constructor runs once per group per solve — 10k times at the G-axis
    envelope — so dataclass/default-factory overhead is measurable)."""
    __slots__ = ("index", "sig", "pods", "reqs", "requests", "masks")

    def __init__(self, index: int, sig: Tuple, pods: List[Pod],
                 reqs: Requirements, requests: Resources,
                 masks: Optional[Dict[int, np.ndarray]] = None):
        self.index = index
        self.sig = sig
        self.pods = pods                 # canonical order
        self.reqs = reqs
        self.requests = requests
        #: ki -> allow mask over interned values (only constrained keys)
        self.masks = masks if masks is not None else {}

    @property
    def count(self) -> int:
        return len(self.pods)

    def __repr__(self):
        return f"PodGroup(index={self.index}, n={len(self.pods)})"


@dataclass
class PoolEncoding:
    index: int
    spec: NodePoolSpec
    type_rows: np.ndarray        # [T] bool — types in this pool's catalog
    agz: np.ndarray              # [Z] bool — allowed zones
    agc: np.ndarray              # [C] bool — allowed capacity types
    masks: Dict[int, np.ndarray]  # label-universe constraints of the pool
    limit_vec: Optional[np.ndarray]  # [D] int64, -1 = unlimited dim
    in_use_vec: np.ndarray       # [D] int64


@dataclass
class SnapshotEncoding:
    """Everything the kernels need, all numpy, all deterministic."""
    universe: LabelUniverse
    dims: List[str]                      # resource dimension names
    zones: List[str]                     # zone names (sorted)
    zone_ids: Dict[str, str]
    types: List[InstanceType]            # the union catalog, name-sorted
    type_names: List[str]
    # tensors
    type_val: np.ndarray                 # [T, K] int32
    A: np.ndarray                        # [T, D] int64 allocatable
    avail: np.ndarray                    # [T, Z, C] bool
    price: np.ndarray                    # [T, Z, C] int64 (PRICE_INF = n/a)
    groups: List[PodGroup]
    R: np.ndarray                        # [G, D] int64 per-pod requests
    n: np.ndarray                        # [G] int64 pod counts
    F: np.ndarray                        # [G, T] bool type-level feasibility
    agz: np.ndarray                      # [G, Z] bool
    agc: np.ndarray                      # [G, C] bool
    pools: List[PoolEncoding]
    admit: np.ndarray                    # [G, P] bool (reqs ∧ taints ∧ residual)
    daemon: np.ndarray                   # [G, P, D] int64 daemon overhead
    # minValues floors (nodepool requirements with a minValues cardinality
    # floor — karpenter.sh_nodepools.yaml:284; enforced per node the way the
    # core scheduler's SatisfiesMinValues check in nodeclaim.Add is). K keys
    # across all pools; each key's (type, value-id) membership pairs drive a
    # segment-max — sharding-friendly (pairs localize per type shard).
    mv_keys: List[str] = field(default_factory=list)
    mv_V: int = 0                        # value-id universe size (max over keys)
    mv_floor: Optional[np.ndarray] = None    # [P, K] int64 (0 = no floor)
    mv_pairs_t: Optional[np.ndarray] = None  # [K, M] int64 type index of pair
    mv_pairs_v: Optional[np.ndarray] = None  # [K, M] int64 value id (V = pad)
    #: any group carries required topology constraints (spread or
    #: required (anti-)affinity) — a pure function of the signatures,
    #: computed from the bank so the solver skips a per-group python scan
    topo_any: bool = False
    #: [G] uint8 — F[g].all() per group (native fill frontier eligibility)
    F_full: Optional[np.ndarray] = None
    #: [G] bool — lazy cache of independent_runs(admit); see fused_runs()
    fuse_prev: Optional[np.ndarray] = None
    #: [G] int64 resolved scheduling priority per group (None when every
    #: pod is priority 0 — the wire then stays Q=0 / prio-free). The
    #: kernel's DECISIONS never read it (canonical order already encodes
    #: priority); it feeds per-tier leftover reporting and the
    #: preemption search's demand selection
    prio: Optional[np.ndarray] = None

    def fused_runs(self) -> np.ndarray:
        """[G] bool ``same_run_as_prev`` over the ADMIT axis: True at g
        means group g's admit row is disjoint from every admit row of
        the greedy run containing g-1, so steps 1-4 of the device scan
        can batch g with that run (ops/ffd_jax.py fused kernel). Pure
        function of ``admit``, computed once per encoding on first use —
        host-only solves never pay the walk."""
        if self.fuse_prev is None:
            self.fuse_prev = independent_runs(self.admit)
        return self.fuse_prev

    @property
    def mv_K(self) -> int:
        return len(self.mv_keys)

    @property
    def mv_M(self) -> int:
        return 0 if self.mv_pairs_t is None else self.mv_pairs_t.shape[1]


def independent_runs(rows: np.ndarray) -> np.ndarray:
    """Greedy maximal runs of pairwise-disjoint boolean rows.

    Returns ``same_run_as_prev`` [G] bool: True at g means row g shares
    no True column with ANY row of the run containing g-1 (tracked as
    the running OR of the current run), so g joins that run; False
    starts a new run at g. Any two rows inside one run are therefore
    pairwise disjoint — the exactness precondition of the fused device
    scan (two groups admitting disjoint pool sets cannot contend for a
    slot, an existing node, or a pool budget, so their fill phases
    commute). An all-False row is disjoint from everything and joins
    any run — which is exactly right for the padded tail groups the
    device buckets append (n=0, admit all-False).

    Greedy maximal is not optimal run-partitioning, but it is O(G*P),
    deterministic, and order-preserving — the scan order IS the FFD
    decision order and must not be permuted."""
    G = rows.shape[0]
    out = np.zeros(G, dtype=bool)
    if G == 0:
        return out
    acc = rows[0].copy()
    for g in range(1, G):
        r = rows[g]
        if not (r & acc).any():
            out[g] = True
            acc |= r
        else:
            acc = r.copy()
    return out


#: C-speed sort key over Pod._nskey (set eagerly in Pod.__init__)
_NSKEY_GET = operator.attrgetter("_nskey")

#: C-accelerated grouping walk (native/groupwalk.c); None -> pure python.
#: The walk reads each pod's cached (epoch, sig-id) pair and buckets by
#: sig id — six C-API calls per pod that cost ~0.7us each as bytecode,
#: the single largest host-engine term at the 50k-pod envelope. The
#: one-shot compile sits neither on the import path nor mid-solve:
#: solver constructors call _groupwalk() to pay it up front (the repo's
#: no-first-solve-latency-cliff convention), and the first grouping
#: builds it only if no solver was constructed first.
_GROUPWALK = None
_GROUPWALK_TRIED = False


def _groupwalk():
    global _GROUPWALK, _GROUPWALK_TRIED
    if not _GROUPWALK_TRIED:
        _GROUPWALK_TRIED = True
        from ..native._build import build_ext_and_import
        _GROUPWALK = build_ext_and_import("karpgroupwalk", "groupwalk.c")
    return _GROUPWALK


#: process-wide signature intern table: sig tuple -> (small id, sig).
#: Grouping then hashes one cached int per pod instead of a deep tuple.
#: Bounded: past _SIG_CAP distinct signatures the table resets and the
#: epoch bumps, invalidating ids cached on pods (a long-lived operator
#: watching churning workloads must not grow memory monotonically).
_SIG_IDS: Dict[Tuple, int] = {}
_SIG_BY_ID: List[Tuple] = []
#: lazily-filled canonical FFD key (-cpu, -mem, digest) per sig id —
#: saves recomputing effective_requests/digest per group per solve
_SIG_KEY_BY_ID: List[Optional[Tuple]] = []
_SIG_EPOCH = 0
_SIG_CAP = 1 << 16
_SIG_MU = threading.Lock()  # two unlocked misses could hand one id to two sigs


def _sig_id(pod: Pod) -> int:
    global _SIG_EPOCH
    ent = pod.__dict__.get("_sig_id")
    if ent is not None and ent[0] == _SIG_EPOCH:
        return ent[1]
    sig = pod_group_signature(pod)
    with _SIG_MU:
        sid = _SIG_IDS.get(sig)
        if sid is None:
            if len(_SIG_BY_ID) >= _SIG_CAP:
                _SIG_IDS.clear()
                _SIG_BY_ID.clear()
                _SIG_KEY_BY_ID.clear()
                _SIG_EPOCH += 1
            sid = len(_SIG_BY_ID)
            _SIG_IDS[sig] = sid
            _SIG_BY_ID.append(sig)
            _SIG_KEY_BY_ID.append(None)
        epoch = _SIG_EPOCH
    pod.__dict__["_sig_id"] = (epoch, sid)
    return sid


def canonical_pod_groups(pods: Sequence[Pod]) -> List[Tuple[Tuple, List[Pod]]]:
    """Group pods by scheduling signature in canonical FFD order.

    Equivalent to ``sorted(pods, key=pod_sort_key)`` followed by dedup —
    but O(n) grouping plus small sorts instead of one n·log(n) sort with
    expensive tuple keys (the 50k-pod sort dominated encode time). Valid
    because pod_sort_key = (-prio, -cpu, -mem, sig_digest, ns, name): all
    members of a group share the leading components (priority is part of
    the signature when nonzero), so sorting groups by the
    representative's key prefix and members by (ns, name) reproduces the
    exact canonical order.
    """
    gw = _groupwalk()
    for _attempt in range(3):
        epoch = _SIG_EPOCH
        by_sid: "Optional[Dict[int, List[Pod]]]" = None
        if gw is not None:
            by_sid, misses = gw.walk(pods, epoch)
            if by_sid is None:
                # cold/stale entries: intern them (computes signatures),
                # then redo the walk — the second pass sees every entry
                # warm unless the table reset mid-way (epoch check below)
                for p in misses:
                    _sig_id(p)
                by_sid, misses = gw.walk(pods, epoch)
        if by_sid is None:
            by_sid = {}
            prev_sid = -1
            bucket: List[Pod] = []
            for p in pods:
                ent = p.__dict__.get("_sig_id")
                sid = ent[1] if (ent is not None and ent[0] == epoch) \
                    else _sig_id(p)
                if sid != prev_sid:  # pods arrive in same-sig runs: skip
                    prev_sid = sid   # the bucket lookup inside a run
                    bucket = by_sid.get(sid)
                    if bucket is None:
                        by_sid[sid] = bucket = []
                bucket.append(p)
        # ids assigned before an intern-table reset collide with ids after
        # it; resolve ids back to sig tuples under the lock, and only if
        # the epoch never moved mid-loop — otherwise the grouping is
        # suspect and we retry (the fresh table now holds this snapshot's
        # sigs, so one retry suffices unless the snapshot alone overflows)
        entries = None
        misses = []
        with _SIG_MU:
            if _SIG_EPOCH == epoch:
                # per-sid FFD keys are cached alongside the intern table:
                # a recurring signature costs one list index instead of
                # effective_requests + digest per solve. Misses are only
                # COLLECTED here — the md5-digest computation runs after
                # the lock drops, so a cold table never serializes
                # concurrent solves on the process-wide intern mutex
                entries = []
                for sid, plist in by_sid.items():
                    key = _SIG_KEY_BY_ID[sid]
                    if key is None:
                        misses.append((len(entries), sid))
                    entries.append((key, _SIG_BY_ID[sid], plist))
        if entries is not None and misses:
            computed = []
            for pos, sid in misses:
                rep = entries[pos][2][0]
                r = rep.effective_requests()
                key = (-getattr(rep, "priority", 0), -r["cpu"],
                       -r["memory"], pod_sig_digest(rep))
                entries[pos] = (key, entries[pos][1], entries[pos][2])
                computed.append((sid, key))
            with _SIG_MU:
                # write-back is idempotent (the key is a pure function of
                # the signature); skip if the table reset meanwhile
                if _SIG_EPOCH == epoch:
                    for sid, key in computed:
                        _SIG_KEY_BY_ID[sid] = key
        if entries is not None:
            # sids are unique within an epoch-stable pass, so no
            # duplicate-signature merge is possible here (the
            # canonical_group_order fallback handles that case)
            for _k, _sig, plist in entries:
                plist.sort(key=_NSKEY_GET)
            entries.sort(key=operator.itemgetter(0))
            return [(sig, plist) for _, sig, plist in entries]
    raw: Dict[Tuple, List[Pod]] = {}
    for p in pods:  # degenerate fallback: group by the raw sig tuple
        raw.setdefault(pod_group_signature(p), []).append(p)
    sig_groups = list(raw.items())
    for _sig, plist in sig_groups:
        plist.sort(key=_NSKEY_GET)
    return canonical_group_order(sig_groups)


def canonical_group_order(
        raw: List[Tuple[Tuple, List[Pod]]]) -> List[Tuple[Tuple, List[Pod]]]:
    """Order (sig, members) groups canonically — by the representative's
    (-prio, -cpu, -mem, sig-digest) FFD key — merging duplicate signatures
    (member lists must each already be (ns, name)-sorted). Shared by the
    full grouping above and the preference wrapper's group-level
    reassembly, so both produce the oracle's exact processing order."""
    by_sig: Dict[Tuple, List[Pod]] = {}
    for sig, plist in raw:
        cur = by_sig.get(sig)
        if cur is None:
            by_sig[sig] = plist
        else:
            # two partitions converged on one signature (e.g. a hardened
            # chain meeting another group's raw spec): the oracle would
            # interleave them by (ns, name) — merge and re-sort
            merged = cur + plist
            merged.sort(key=_NSKEY_GET)
            by_sig[sig] = merged
    entries = []
    for sig, plist in by_sig.items():
        rep = plist[0]
        r = rep.effective_requests()
        entries.append(((-getattr(rep, "priority", 0), -r["cpu"],
                         -r["memory"], pod_sig_digest(rep)), sig, plist))
    entries.sort(key=lambda e: e[0])
    return [(sig, plist) for _, sig, plist in entries]


@dataclass
class _CatalogEncoding:
    """Catalog-side tensors, reused while the catalog objects are stable.

    Everything here derives from the instance-type objects + the zone map
    alone — not from pods — and the instancetype provider hands out the
    SAME objects until a catalog/offerings seqnum bump rebuilds them
    (instancetype.go:119-130 cache-key discipline). Caching by object
    identity therefore invalidates exactly when the provider does, and
    removes the O(T x requirements) interning + O(T x Z x C) offerings
    assembly from the per-solve hot path (at ~850 types that was most of
    encode time)."""
    universe: LabelUniverse
    types: List[InstanceType]
    type_names: List[str]
    #: id(resolved InstanceType) -> column (names repeat across variants)
    type_pos: Dict[int, int]
    type_val: np.ndarray
    A: np.ndarray
    avail: np.ndarray
    price: np.ndarray
    zones: List[str]
    zid_of: Dict[str, str]


_CATALOG_CACHE: Dict[Tuple, _CatalogEncoding] = {}
_CATALOG_CACHE_CAP = 8
_CATALOG_MU = threading.Lock()
#: per-catalog-encoding signature->group-row cache bound (a long-lived
#: operator watching churning workloads must not grow memory monotonically)
_GROUP_ROW_CACHE_CAP = 1 << 16


class _RowBank:
    """Signature-keyed per-group row store with contiguous bank matrices.

    ``idx`` maps a scheduling signature to its row in the banks; warm
    encode assembly is then one fancy-index gather per tensor instead of
    a python loop of per-row copies. Banks double geometrically; rows are
    immutable once written.

    Lifetime contract with RESIDENT encodings (models/delta.py): every
    per-group tensor an encoding carries is a fancy-index GATHER — a
    copy — and ``g.masks`` holds the mask dict by reference, so neither
    ``reset()`` (which clears ``idx``/``masks``/``size`` but keeps the
    matrices and ``pins``, letting later adds overwrite rows from 0) nor
    ``_grow()`` (which copies the filled prefix into doubled matrices,
    preserving row order) can mutate an encoding that has already been
    assembled. ``add()`` writes every bank column of its row, so a
    recycled post-reset row can never leak a stale field. The regression
    suite in tests/test_delta_encoding.py pins all three properties."""

    def __init__(self, T: int, Z: int, C: int, P: int, D: int, pins=()):
        self.idx: Dict[Tuple, int] = {}
        self.size = 0
        self.masks: List[Dict[int, np.ndarray]] = []
        self.pins = pins
        cap = 256
        self.R = np.zeros((cap, D), dtype=np.int64)
        self.F = np.zeros((cap, T), dtype=bool)
        self.agz = np.zeros((cap, Z), dtype=bool)
        self.agc = np.zeros((cap, C), dtype=bool)
        self.admit = np.zeros((cap, P), dtype=bool)
        self.daemon = np.zeros((cap, P, D), dtype=np.int64)
        self.topo = np.zeros(cap, dtype=bool)
        self.F_full = np.zeros(cap, dtype=np.uint8)

    def _grow(self):
        for name in ("R", "F", "agz", "agc", "admit", "daemon", "topo",
                     "F_full"):
            a = getattr(self, name)
            b = np.zeros((a.shape[0] * 2,) + a.shape[1:], dtype=a.dtype)
            b[:a.shape[0]] = a
            setattr(self, name, b)

    def reset(self):
        self.idx.clear()
        self.masks.clear()
        self.size = 0

    def add(self, sig: Tuple, Rrow, masks, Frow, agzrow, agcrow,
            admit_row, daemon_rows, topo_flag: bool) -> int:
        i = self.size
        if i >= self.R.shape[0]:
            self._grow()
        self.R[i] = Rrow
        self.F[i] = Frow
        self.agz[i] = agzrow
        self.agc[i] = agcrow
        self.admit[i] = admit_row
        self.daemon[i] = daemon_rows
        self.topo[i] = topo_flag
        self.F_full[i] = 1 if Frow.all() else 0
        self.masks.append(masks)
        self.idx[sig] = i
        self.size = i + 1
        return i


def _encode_catalog(seen: Dict[Tuple[str, int], InstanceType],
                    snapshot_zones: Tuple[Tuple[str, str], ...],
                    dims: Tuple[str, ...]) -> _CatalogEncoding:
    types = [seen[k] for k in sorted(seen)]
    key = (tuple(id(t) for t in types), snapshot_zones, dims)
    with _CATALOG_MU:
        hit = _CATALOG_CACHE.get(key)
        if hit is not None:
            return hit
    universe = LabelUniverse(types)
    type_val = universe.type_value_ids(types)
    dpos = {d: i for i, d in enumerate(dims)}
    zone_set: Set[str] = {z for z, _ in snapshot_zones}
    zid_of: Dict[str, str] = dict(snapshot_zones)
    for t in types:
        for o in t.offerings:
            zone_set.add(o.zone)
            if o.zone_id:
                zid_of.setdefault(o.zone, o.zone_id)
    zones = sorted(zone_set)
    zpos = {z: i for i, z in enumerate(zones)}
    Z, C, T, D = len(zones), len(CAPACITY_TYPES), len(types), len(dims)
    cpos = {c: i for i, c in enumerate(CAPACITY_TYPES)}
    avail = np.zeros((T, Z, C), dtype=bool)
    price = np.full((T, Z, C), PRICE_INF, dtype=np.int64)
    A = np.zeros((T, D), dtype=np.int64)
    for ti, t in enumerate(types):
        for k, q in t.allocatable().items():
            i = dpos.get(k)
            if i is not None:
                A[ti, i] = q
        for o in t.offerings:
            zi, ci = zpos[o.zone], cpos[o.capacity_type]
            price[ti, zi, ci] = o.price
            if o.available:
                avail[ti, zi, ci] = True
    enc = _CatalogEncoding(
        universe=universe, types=types,
        type_names=[t.name for t in types],
        type_pos={id(t): i for i, t in enumerate(types)},
        type_val=type_val, A=A, avail=avail, price=price,
        zones=zones, zid_of=zid_of)
    with _CATALOG_MU:
        if len(_CATALOG_CACHE) >= _CATALOG_CACHE_CAP:
            _CATALOG_CACHE.clear()  # tiny cache; staleness-by-identity only
        _CATALOG_CACHE[key] = enc
    return enc


def resource_vec(r: Resources, D: int, dpos: Mapping[str, int]) -> np.ndarray:
    """[D] int64 of one ``Resources`` over the encoding's dim order."""
    v = np.zeros(D, dtype=np.int64)
    for k, q in r.items():
        i = dpos.get(k)
        if i is not None:
            v[i] = q
    return v


def pool_dynamic_vecs(spec: NodePoolSpec, D: int, dpos: Mapping[str, int]):
    """(limit_vec, in_use_vec) of one pool — the per-tick-DYNAMIC half of
    ``PoolEncoding``: ``in_use`` moves every reconcile round and limits
    can be edited, while everything else in the pool row is stable for
    as long as the nodepool/catalog objects are. One derivation shared
    by ``encode_snapshot`` and the incremental patcher (models/delta.py)
    so the resident arena and a from-scratch encode can never disagree
    on the pool tensors."""
    limits = spec.nodepool.limits
    lim_vec = None
    if limits is not None:
        lim_vec = np.full(D, -1, dtype=np.int64)
        for k, q in limits.items():
            if k in dpos:
                lim_vec[dpos[k]] = q
    return lim_vec, resource_vec(spec.in_use, D, dpos)


def encode_snapshot(snapshot: SchedulingSnapshot,
                    pod_groups: Optional[List[Tuple[Tuple, List[Pod]]]] = None
                    ) -> SnapshotEncoding:
    # --- groups (canonical FFD order, O(n) grouping) ----------------------
    # the preference wrapper already walked every pod to group them; when
    # it hands the grouping down, the second 50k-pod walk disappears
    groups: List[PodGroup] = []
    dims_set = {"cpu", "memory", "pods"}
    for sig, plist in (pod_groups if pod_groups is not None
                       else canonical_pod_groups(snapshot.pods)):
        rep = plist[0]
        req = rep.effective_requests()
        dims_set.update(req.nonzero_keys())
        groups.append(PodGroup(len(groups), sig, plist,
                               rep.scheduling_requirements(), req))

    # --- union catalog --------------------------------------------------
    # Dedup by RESOLVED OBJECT, not by name: the same type name resolves
    # differently under different NodeClasses (windows vs linux OS
    # labels, kubelet-dependent allocatable), and a name-keyed union lets
    # one pool's variant poison another's requirements/capacity. Pools
    # sharing a NodeClass share the provider's cached objects, so the
    # common case still dedups to one column. Variant indices follow
    # first-seen order (snapshot pool order) — deterministic.
    seen: Dict[Tuple[str, int], InstanceType] = {}
    seen_ids: Set[int] = set()
    _variant_count: Dict[str, int] = {}
    for spec in snapshot.nodepools:
        for t in spec.instance_types:
            if id(t) in seen_ids:
                continue
            v = _variant_count.get(t.name, 0)
            _variant_count[t.name] = v + 1
            seen[(t.name, v)] = t
            seen_ids.add(id(t))

    # --- dims (group keys folded in during the grouping walk above) ------
    for d in snapshot.daemon_overheads:
        dims_set.update(d.requests.nonzero_keys())
    for spec in snapshot.nodepools:
        if spec.nodepool.limits is not None:
            dims_set.update(spec.nodepool.limits.nonzero_keys())
    dims = sorted(dims_set)
    dpos = {d: i for i, d in enumerate(dims)}

    def vec(r: Resources) -> np.ndarray:
        return resource_vec(r, len(dims), dpos)

    # --- catalog tensors (cached while the type objects are stable) ------
    cenc = _encode_catalog(
        seen, tuple(sorted(snapshot.zones.items())), tuple(dims))
    types, type_pos = cenc.types, cenc.type_pos
    universe, type_val = cenc.universe, cenc.type_val
    zones, zid_of = cenc.zones, cenc.zid_of
    A, avail, price = cenc.A, cenc.avail, cenc.price
    Z, C, T, D = len(zones), len(CAPACITY_TYPES), len(types), len(dims)

    # --- pools ----------------------------------------------------------
    pools: List[PoolEncoding] = []
    ordered_specs = sorted(
        snapshot.nodepools,
        key=lambda s: (-s.nodepool.weight, s.nodepool.metadata.name))
    for pi, spec in enumerate(ordered_specs):
        rows = np.zeros(T, dtype=bool)
        for t in spec.instance_types:
            rows[type_pos[id(t)]] = True
        preqs = spec.nodepool.scheduling_requirements()
        # the pool's own label requirements restrict the type axis, exactly
        # like the oracle's merged-requirement conflict check does
        for ki, mask in universe.group_masks(preqs).items():
            rows &= mask[type_val[:, ki]]
        lim_vec, iu_vec = pool_dynamic_vecs(spec, D, dpos)
        pools.append(PoolEncoding(
            index=pi, spec=spec, type_rows=rows,
            agz=_zone_allow(preqs, zones, zid_of),
            agc=_ct_allow(preqs),
            masks=universe.group_masks(preqs),
            limit_vec=lim_vec,
            in_use_vec=iu_vec))
    P = len(pools)

    # --- group tensors (signature-keyed row bank) ------------------------
    # Everything per-group here is a pure function of (scheduling
    # signature, catalog encoding, pool set, daemon set, dims): cache the
    # rows on the catalog encoding so recurring signatures — steady-state
    # reconcile rounds, preference-relaxation re-solves, and the
    # high-cardinality G axis — skip the requirements algebra entirely.
    # Keyed by object identity for pools/daemons (the same staleness
    # discipline as _CATALOG_CACHE: providers hand out stable objects
    # until a seqnum bump rebuilds them). Rows live in contiguous bank
    # matrices so warm assembly is G fancy-index gathers, not a
    # G-iteration python loop of row copies (at 10k signatures the loop
    # was most of encode time).
    banks = getattr(cenc, "_row_banks", None)
    if banks is None:
        banks = cenc._row_banks = {}
    pkey = (tuple(id(spec.nodepool) for spec in ordered_specs),
            tuple(id(d) for d in snapshot.daemon_overheads),
            tuple(dims))
    bank = banks.get(pkey)
    if bank is not None and bank.size >= _GROUP_ROW_CACHE_CAP:
        # cap enforcement happens BETWEEN encodes only: a mid-encode
        # reset would let later adds overwrite bank rows this encode's
        # gather indices already reference
        bank.reset()
    if bank is None:
        if sum(b.size for b in banks.values()) >= _GROUP_ROW_CACHE_CAP:
            banks.clear()
        # the pins hold the id()-keyed pool/daemon objects alive for the
        # bank's lifetime: a GC'd pool whose address CPython recycles for
        # a NEW pool must never alias an old key (same discipline as
        # _CATALOG_CACHE pinning its types)
        bank = banks[pkey] = _RowBank(
            T=T, Z=Z, C=C, P=P, D=D,
            pins=(tuple(spec.nodepool for spec in ordered_specs),
                  tuple(snapshot.daemon_overheads)))
    G = len(groups)
    n = np.empty(G, dtype=np.int64)
    idxs = np.empty(G, dtype=np.int64)
    bank_idx = bank.idx
    for g in groups:
        n[g.index] = g.count
        bi = bank_idx.get(g.sig)
        if bi is None:
            Rrow = vec(g.requests)
            masks = universe.group_masks(g.reqs)
            Frow = np.ones(T, dtype=bool)
            for ki, mask in masks.items():
                Frow &= mask[type_val[:, ki]]
            agzrow = _zone_allow(g.reqs, zones, zid_of)
            agcrow = _ct_allow(g.reqs)
            admit_row = np.zeros(P, dtype=bool)
            daemon_rows = np.zeros((P, D), dtype=np.int64)
            pod = g.pods[0]
            for pe in pools:
                np_obj = pe.spec.nodepool
                base = np_obj.scheduling_requirements()
                if base.compatible(g.reqs):
                    continue
                if not all(t.tolerated_by(pod.tolerations)
                           for t in np_obj.template.taints):
                    continue
                merged = base.union(g.reqs)
                if any(r.unsatisfiable() for r in merged):
                    continue
                admit_row[pe.index] = True
                total = Resources()
                for d in snapshot.daemon_overheads:
                    if not merged.compatible(d.requirements):
                        total = total + d.requests
                daemon_rows[pe.index] = vec(total)
            topo_flag = bool(pod.topology_spread) or \
                any(a.required for a in pod.pod_affinity)
            bi = bank.add(g.sig, Rrow, masks, Frow, agzrow, agcrow,
                          admit_row, daemon_rows, topo_flag)
        g.masks = bank.masks[bi]
        idxs[g.index] = bi
    R = bank.R[idxs]
    F = bank.F[idxs]
    agz = bank.agz[idxs]
    agc = bank.agc[idxs]
    admit = bank.admit[idxs]
    daemon = bank.daemon[idxs]
    topo_any = bool(bank.topo[idxs].any())
    F_full = np.ascontiguousarray(bank.F_full[idxs])

    mv_keys, mv_V, mv_floor, mv_pairs_t, mv_pairs_v = \
        _encode_min_values(pools, types, P)

    # per-group resolved priority: None while every pod is priority 0 so
    # priority-free snapshots stay wire-identical (statics Q=0, no prio
    # section). Priority is part of the signature when nonzero, so the
    # representative speaks for the whole group.
    prio = None
    if any(getattr(g.pods[0], "priority", 0) for g in groups):
        prio = np.zeros(G, dtype=np.int64)
        for g in groups:
            prio[g.index] = getattr(g.pods[0], "priority", 0)

    return SnapshotEncoding(
        universe=universe, dims=dims, zones=zones, zone_ids=zid_of,
        types=types, type_names=cenc.type_names,
        type_val=type_val, A=A, avail=avail, price=price,
        groups=groups, R=R, n=n, F=F, agz=agz, agc=agc,
        pools=pools, admit=admit, daemon=daemon,
        mv_keys=mv_keys, mv_V=mv_V, mv_floor=mv_floor,
        mv_pairs_t=mv_pairs_t, mv_pairs_v=mv_pairs_v,
        topo_any=topo_any, F_full=F_full, prio=prio)


def _encode_min_values(pools: List[PoolEncoding],
                       types: Sequence[InstanceType], P: int):
    """Pool-level minValues floors + per-key (type, value) membership pairs.

    Value ids are interned per key over the values each type's requirement
    carries (multi-valued requirements contribute one pair per value — the
    same union-cardinality the launch-path truncation counts). Pairs are
    padded with value id V, a dump segment sliced off by the kernels.
    """
    keys = sorted({r.key for pe in pools
                   for r in pe.spec.nodepool.scheduling_requirements()
                   if r.min_values is not None})
    if not keys:
        return [], 0, None, None, None
    K = len(keys)
    mv_floor = np.zeros((P, K), dtype=np.int64)
    for pe in pools:
        for r in pe.spec.nodepool.scheduling_requirements():
            if r.min_values is not None:
                mv_floor[pe.index, keys.index(r.key)] = r.min_values
    pairs: List[List[Tuple[int, int]]] = []
    V = 0
    for key in keys:
        vids: Dict[str, int] = {}
        kp: List[Tuple[int, int]] = []
        for ti, t in enumerate(types):
            r = t.requirements.get(key)
            if r is None or r.complement:
                continue
            for v in sorted(r.values):
                vid = vids.setdefault(v, len(vids))
                kp.append((ti, vid))
        pairs.append(kp)
        V = max(V, len(vids))
    M = max((len(kp) for kp in pairs), default=0)
    mv_pairs_t = np.zeros((K, M), dtype=np.int64)
    mv_pairs_v = np.full((K, M), V, dtype=np.int64)  # pad -> dump segment
    for ki, kp in enumerate(pairs):
        for mi, (ti, vid) in enumerate(kp):
            mv_pairs_t[ki, mi] = ti
            mv_pairs_v[ki, mi] = vid
    return keys, V, mv_floor, mv_pairs_t, mv_pairs_v


def _zone_allow(reqs: Requirements, zones: List[str],
                zid_of: Mapping[str, str]) -> np.ndarray:
    mask = np.ones(len(zones), dtype=bool)
    zr = reqs.get(L.ZONE)
    if zr is not None:
        mask &= np.array([zr.has(z) for z in zones])
    zir = reqs.get(L.ZONE_ID)
    if zir is not None:
        mask &= np.array([zir.has(zid_of.get(z, "")) for z in zones])
    return mask


def _ct_allow(reqs: Requirements) -> np.ndarray:
    mask = np.ones(len(CAPACITY_TYPES), dtype=bool)
    ctr = reqs.get(L.CAPACITY_TYPE)
    if ctr is not None:
        mask &= np.array([ctr.has(c) for c in CAPACITY_TYPES])
    return mask
