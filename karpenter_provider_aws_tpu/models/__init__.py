from .encoding import (CAPACITY_TYPES, PRICE_INF, LabelUniverse, PodGroup,
                       PoolEncoding, SnapshotEncoding, encode_snapshot)

__all__ = ["encode_snapshot", "SnapshotEncoding", "LabelUniverse", "PodGroup",
           "PoolEncoding", "CAPACITY_TYPES", "PRICE_INF"]
