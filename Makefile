# Developer workflow targets (the reference's Makefile surface:
# presubmit/test, deflake with randomized ordering, benchmark).

PYTEST ?= python -m pytest

test: native-try  ## fast tier: everything but the scale envelopes (<~3min)
	$(PYTEST) tests/ -x -q -m "not scale"

native:  ## build the native host libraries (codec, fastfill, deltawalk)
	$(MAKE) -C native all

native-try:  ## best-effort native build: missing toolchain is NOT an error
	-@$(MAKE) -C native all 2>/dev/null || \
	  echo "native build unavailable (no toolchain?); numpy twins serve"

aot-prime:  ## pre-build the XLA:CPU AOT store for THIS host's ISA
	python hack/aotprime.py

test-all:  ## every suite including the scale tier
	$(PYTEST) tests/ -x -q

scale:  ## the scale tier alone (55k pods, deprovisioning, chaos)
	$(PYTEST) tests/ -x -q -m scale

deflake:  ## Makefile:63-70 analog: randomized order, repeated until failure
	for i in 1 2 3 4 5; do \
	  KARPENTER_TEST_SHUFFLE_SEED=$$i $(PYTEST) tests/ -q -x -m "not scale" || exit 1; \
	done

chart:  ## render + lint the deploy chart (no helm needed)
	python hack/render_chart.py --validate

chaos:  ## both seeded fault-injection sweeps (solver wire + cloud seam)
	sh hack/chaoswire.sh
	sh hack/chaoscloud.sh

chaoscloud:  ## the 10-seed cloud-seam chaos sweep alone
	sh hack/chaoscloud.sh

chaos-tenant:  ## hostile-tenant isolation sweep (quiet tenant vs hammer)
	sh hack/chaostenant.sh

chaos-patch:  ## 10-seed delta-wire chaos sweep (SolvePatch degradations)
	sh hack/chaospatch.sh

chaos-fleet:  ## seeded fleet chaos sweep (kill/flap/roll replicas)
	sh hack/chaosfleet.sh

chaos-heal:  ## seeded self-heal storm (kill/wedge workers, supervised regroup)
	sh hack/chaosheal.sh

fuzz-delta:  ## 10-seed mutation-sequence fuzz of the incremental encoder
	sh hack/fuzzdelta.sh

fuzz-suffix:  ## 10-seed churn fuzz + kernel byte-parity sweep of the incremental solve
	sh hack/fuzzsuffix.sh

fuzz-consolidate:  ## seeded device-vs-oracle consolidation parity sweep
	sh hack/fuzzconsolidate.sh

fuzz-preempt:  ## seeded device-vs-oracle preemption parity sweep
	sh hack/fuzzpreempt.sh

sim:  ## endurance replay: 24 virtual hours + chaos in <=10 min wall
	sh hack/sim.sh

benchmark: native-try  ## the five BASELINE configs + interruption + batch dispatch
	python bench.py --all --rounds 100
	python bench.py --warm-tick
	python bench.py --interruption
	python bench.py --batch-solve
	python bench.py --sidecar-batch
	python bench.py --delta-solve
	python bench.py --patch-wire
	python bench.py --tenant-mix
	python bench.py --mesh-batch
	python bench.py --multihost --rounds 5
	python bench.py --fleet
	python bench.py --consolidate-solve --consolidate-nodes 240 --rounds 5
	python bench.py --preempt-solve --rounds 5

consolidate-evidence:  ## full 1000-node fleet: 2000 lanes, ONE dispatch/round
	# a 1000-node round is a single stacked subset dispatch regardless of
	# fleet size; the host-CPU twin serializes the 2048 lanes (~minutes),
	# a real device amortizes them — run this variant on accelerator hosts
	python bench.py --consolidate-solve --rounds 3

multichip:  ## multi-device solve: driver dryrun + mesh parity suites
	sh hack/multichip.sh

multihost:  ## multi-PROCESS distributed mesh: 1M-pod ceiling + chaos + suite
	sh hack/multihost.sh

daemon:  ## run the operator against the in-memory cloud
	python -m karpenter_provider_aws_tpu --cluster-name dev --metrics-port 8080

.PHONY: test test-all scale deflake benchmark consolidate-evidence multichip multihost daemon chart chaos chaoscloud chaos-tenant chaos-patch chaos-fleet chaos-heal fuzz-delta fuzz-suffix fuzz-consolidate fuzz-preempt native native-try aot-prime sim
