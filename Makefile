# Developer workflow targets (the reference's Makefile surface:
# presubmit/test, deflake with randomized ordering, benchmark).

PYTEST ?= python -m pytest

test:  ## unit + component suites (virtual 8-device CPU mesh)
	$(PYTEST) tests/ -x -q

scale:  ## the scale suite alone (55k pods, deprovisioning, chaos)
	$(PYTEST) tests/test_scale_suite.py -x -q

deflake:  ## Makefile:63-70 analog: randomized order, repeated until failure
	for i in 1 2 3 4 5; do \
	  KARPENTER_TEST_SHUFFLE_SEED=$$i $(PYTEST) tests/ -q -x || exit 1; \
	done

benchmark:  ## the five BASELINE configs + interruption throughput
	python bench.py --all --rounds 100
	python bench.py --interruption

multichip:  ## dry-run the multi-device solve on 8 virtual CPU devices
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

daemon:  ## run the operator against the in-memory cloud
	python -m karpenter_provider_aws_tpu --cluster-name dev --metrics-port 8080

.PHONY: test scale deflake benchmark multichip daemon
